"""The pure JAX MANO forward core.

One pure function over a frozen ``ManoParams`` PyTree — jittable, vmappable,
and differentiable end-to-end (SURVEY.md §7 design stance). The math is the
reference pipeline (/root/reference/mano_np.py:79-115) re-composed from the
TPU-first ops in ``mano_hand_tpu.ops``:

    shape_blend -> regress_joints -> rotation_matrix -> pose_blend
    -> forward_kinematics (level-parallel) -> skin (fused LBS)

Batching is by ``jax.vmap`` over the pose/shape arguments (params are closed
over and replicated); huge batches go through ``forward_chunked`` to bound
the [B, V, 3, 3] blend-rotation intermediate.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from mano_hand_tpu.assets.schema import ManoParams
from mano_hand_tpu import constants, ops
from mano_hand_tpu.ops.common import DEFAULT_PRECISION


class ManoOutput(NamedTuple):
    """Forward-pass outputs; mirrors the reference's exposed state
    (verts/J/R/rest_verts at /root/reference/mano_np.py:41-44) plus posed
    joint locations."""

    verts: jnp.ndarray         # [..., V, 3] skinned mesh
    joints: jnp.ndarray        # [..., J, 3] rest-pose joints
    rest_verts: jnp.ndarray    # [..., V, 3] blendshaped mesh pre-skinning
    rot_mats: jnp.ndarray      # [..., J, 3, 3] per-joint rotations
    posed_joints: jnp.ndarray  # [..., J, 3] world joints after FK


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class ShapedHand:
    """A subject's shape stage, baked once by ``specialize``.

    The MANO forward factors cleanly at the shape/pose boundary
    (/root/reference/mano_np.py:81-83 vs 87-115): ``v_shaped`` and the
    rest joints depend ONLY on beta, while the pose stage (pose blend,
    FK, LBS) consumes them plus the pose. This PyTree carries everything
    the pose stage needs — the baked shape constants AND the
    shape-independent parameter leaves (referenced, not copied) — so
    ``forward_posed(shaped, pose)`` is self-contained. A registered
    dataclass like ``ManoParams``: jit/vmap/grad-friendly, ``parents``
    static aux data.
    """

    v_shaped: Any      # [V, 3] shape-blendshaped template (mano_np.py:81)
    joints: Any        # [J, 3] rest joints, Jreg @ v_shaped (mano_np.py:83)
    shape: Any         # [S] the baked betas (provenance / LMResult.shape)
    pose_basis: Any    # [V, 3, P] pose-corrective basis (shared leaf)
    lbs_weights: Any   # [V, J] skinning weights (shared leaf)
    parents: Tuple[int, ...] = dataclasses.field(
        default=constants.MANO_PARENTS, metadata={"static": True}
    )

    @property
    def n_joints(self) -> int:
        return self.joints.shape[-2]

    @property
    def n_verts(self) -> int:
        return self.v_shaped.shape[-2]


def specialize(
    params: ManoParams,
    shape: Optional[jnp.ndarray] = None,  # [S]
    precision=DEFAULT_PRECISION,
) -> ShapedHand:
    """Bake one subject's betas into a :class:`ShapedHand`.

    Runs EXACTLY the shape stage of ``forward_rotmats`` — the same
    ``ops.shape_blend`` / ``ops.regress_joints`` calls at the same
    precision — so ``forward_posed(specialize(params, beta), pose)`` is
    bit-identical to ``forward(params, pose, beta)`` in the same
    precision/batching context (pinned in tests/test_specialize.py).
    The serving pattern: per-subject traffic (frame-to-frame tracking,
    per-user inference) holds beta fixed across thousands of calls, so
    the shape stage is paid once here instead of per call. Batch over
    subjects with ``jax.vmap`` over ``shape`` (params closed over) —
    but note the shared basis leaves are then broadcast per row; for a
    one-subject stream keep ONE ShapedHand and batch only the pose.
    """
    dtype = params.v_template.dtype
    if shape is None:
        shape = jnp.zeros((params.shape_basis.shape[-1],), dtype=dtype)
    shape = jnp.asarray(shape).astype(dtype)
    v_shaped = ops.shape_blend(
        params.v_template, params.shape_basis, shape, precision
    )
    joints = ops.regress_joints(params.j_regressor, v_shaped, precision)
    return ShapedHand(
        v_shaped=v_shaped,
        joints=joints,
        shape=shape,
        pose_basis=params.pose_basis,
        lbs_weights=params.lbs_weights,
        parents=params.parents,
    )


def _check_compute_dtype(compute_dtype) -> None:
    """bfloat16 or None, nothing else — the stated PR-14 policy. The
    fused kernel already enforces this (ops/pallas_posed.py); the XLA
    entries must too, or e.g. float16/float64 compute would serve
    under bf16-documented claims with no stated envelope (and outside
    the jaxpr audit, which traces only the committed specs)."""
    if compute_dtype is not None and \
            jnp.dtype(compute_dtype) != jnp.bfloat16:
        raise ValueError(
            f"compute_dtype must be bfloat16 (the serving bf16 tier) "
            f"or None, got {compute_dtype}")


def forward_posed(
    shaped: ShapedHand,
    pose: Optional[jnp.ndarray] = None,   # [J, 3] axis-angle, row 0 global
    precision=DEFAULT_PRECISION,
    compute_dtype=None,
) -> ManoOutput:
    """Pose-only forward over a baked shape stage.

    The second half of the ``specialize``/``forward_posed`` split: pose
    blend -> FK -> LBS (/root/reference/mano_np.py:87-115), identical
    op-for-op to the corresponding stages of ``forward`` — so the output
    is bit-identical to the full path under the same precision and
    batching structure, while skipping the per-call shape blend and
    joint regression entirely. Batch with ``jax.vmap`` over ``pose``
    (one subject, many poses) — the steady-state serving shape.

    ``compute_dtype`` (PR 14, the serving bf16 tier): when set (bf16),
    the MXU-bound contractions of the pose stage — pose-corrective
    blend and LBS skinning — run with operands cast to that dtype and
    f32 accumulation, while Rodrigues, FK, and every residual add stay
    f32 and the returned vertices are f32 (~4e-4 m max vertex error vs
    the f32 path, measured on this stack; the PrecisionPolicy envelope
    in serving/precision.py states the budget).
    """
    n_joints = shaped.joints.shape[0]
    dtype = shaped.v_shaped.dtype
    if pose is None:
        pose = jnp.zeros((n_joints, 3), dtype=dtype)
    pose = pose.reshape(n_joints, 3).astype(dtype)
    return forward_posed_rotmats(shaped, ops.rotation_matrix(pose),
                                 precision, compute_dtype)


def forward_posed_rotmats(
    shaped: ShapedHand,
    rot_mats: jnp.ndarray,   # [J, 3, 3] per-joint rotations, row 0 global
    precision=DEFAULT_PRECISION,
    compute_dtype=None,
) -> ManoOutput:
    """Pose-only forward from rotation MATRICES (``forward_posed`` minus
    Rodrigues — same input contract as ``forward_rotmats``).
    ``compute_dtype`` as in ``forward_posed``: bf16 contraction
    operands with f32 accumulation on the two MXU-bound stages only."""
    _check_compute_dtype(compute_dtype)
    n_joints = shaped.joints.shape[0]
    dtype = shaped.v_shaped.dtype
    rot_mats = rot_mats.reshape(n_joints, 3, 3).astype(dtype)
    v_posed = ops.pose_blend(
        shaped.v_shaped, shaped.pose_basis, rot_mats, precision,
        compute_dtype=compute_dtype,
    )
    world_rot, world_t = ops.forward_kinematics(
        shaped.parents, rot_mats, shaped.joints, precision
    )
    skin_rot, skin_t = ops.skinning_transforms(
        world_rot, world_t, shaped.joints, precision
    )
    verts = ops.skin(shaped.lbs_weights, skin_rot, skin_t, v_posed,
                     precision, compute_dtype=compute_dtype)
    return ManoOutput(
        verts=verts,
        joints=shaped.joints,
        rest_verts=v_posed,
        rot_mats=rot_mats,
        posed_joints=world_t,
    )


def forward_posed_batched(
    shaped: ShapedHand,
    pose: jnp.ndarray,       # [B, J, 3]
    precision=DEFAULT_PRECISION,
) -> ManoOutput:
    """vmap the pose-only forward over a pose batch; the ShapedHand is
    closed over (ONE subject's constants shared by every row — the
    steady-state serving/tracking shape). Results match a direct
    ``forward_batched(params, pose, broadcast(beta), fused=False)`` to
    float rounding (the shared-vs-per-row shape stage changes batched
    contraction shapes by design; the bit-identity contract holds at
    matched batching structure — tests/test_specialize.py)."""
    pose = pose.reshape(pose.shape[0], -1, 3)
    return jax.vmap(lambda q: forward_posed(shaped, q, precision))(pose)


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class SubjectTable:
    """A device-resident stack of baked shape stages (PR-4 tentpole).

    Every ``specialize()``d subject becomes one ROW of the per-subject
    leaves (``v_shaped [C, V, 3]``, ``joints [C, J, 3]``,
    ``shape [C, S]``); the shape-independent parameter leaves
    (``pose_basis``, ``lbs_weights``) are stored ONCE, unbatched — they
    are identical for every subject, and keeping them out of the
    per-row axis is also what makes ``forward_posed_gather``
    bit-identical to the shared-ShapedHand posed program (the shared
    leaves enter the same contractions with the same shapes).

    ``C`` is a CAPACITY, not an occupancy: the serving engine grows it
    by doubling, so the gathered programs — whose shapes depend only on
    (C, bucket) — recompile ``O(log subjects)`` times, and an LRU
    eviction merely rewrites a row (a data operation; no program ever
    sees which rows are live). All row updates are FUNCTIONAL
    (``table_set_row`` returns a new table); a snapshot captured by an
    in-flight dispatch therefore stays valid however the live table
    mutates behind it.
    """

    v_shaped: Any      # [C, V, 3] per-subject shaped templates
    joints: Any        # [C, J, 3] per-subject rest joints
    shape: Any         # [C, S] the baked betas per subject (provenance)
    pose_basis: Any    # [V, 3, P] pose-corrective basis (shared, unbatched)
    lbs_weights: Any   # [V, J] skinning weights (shared, unbatched)
    parents: Tuple[int, ...] = dataclasses.field(
        default=constants.MANO_PARENTS, metadata={"static": True}
    )

    @property
    def capacity(self) -> int:
        return self.v_shaped.shape[0]

    @property
    def n_joints(self) -> int:
        return self.joints.shape[-2]

    @property
    def n_verts(self) -> int:
        return self.v_shaped.shape[-2]


def subject_table(params: ManoParams, capacity: int = 1) -> SubjectTable:
    """An empty (zero-row) :class:`SubjectTable` over ``params``.

    Rows are populated with ``table_set_row``; unwritten rows are zeros
    and harmless — the gather index decides which rows a program ever
    reads, and the engine never hands out an unwritten slot.
    """
    if capacity < 1:
        raise ValueError(f"capacity must be >= 1, got {capacity}")
    dtype = params.v_template.dtype
    n_v = params.v_template.shape[0]
    n_j = params.j_regressor.shape[0]
    n_s = params.shape_basis.shape[-1]
    return SubjectTable(
        v_shaped=jnp.zeros((capacity, n_v, 3), dtype),
        joints=jnp.zeros((capacity, n_j, 3), dtype),
        shape=jnp.zeros((capacity, n_s), dtype),
        pose_basis=params.pose_basis,
        lbs_weights=params.lbs_weights,
        parents=params.parents,
    )


def stack_shaped(shaped: Sequence[ShapedHand]) -> SubjectTable:
    """Stack ``specialize``d hands into a :class:`SubjectTable` (capacity
    == len(shaped)). The shared leaves are taken from the first entry —
    they are parameter leaves, identical across subjects of one asset;
    stacking hands from DIFFERENT assets is a caller error."""
    if not shaped:
        raise ValueError("need at least one ShapedHand to stack")
    first = shaped[0]
    for s in shaped[1:]:
        if tuple(s.parents) != tuple(first.parents):
            raise ValueError(
                "cannot stack ShapedHands with different kinematic trees")
    return SubjectTable(
        v_shaped=jnp.stack([s.v_shaped for s in shaped]),
        joints=jnp.stack([s.joints for s in shaped]),
        shape=jnp.stack([s.shape for s in shaped]),
        pose_basis=first.pose_basis,
        lbs_weights=first.lbs_weights,
        parents=first.parents,
    )


def table_set_row(table: SubjectTable, slot, shaped: ShapedHand,
                  ) -> SubjectTable:
    """Write one subject's baked constants into row ``slot`` — FUNCTIONAL
    (returns a new table; the input is untouched, so snapshots held by
    in-flight dispatches stay valid). ``slot`` may be a traced int32
    scalar: one compiled update program covers every slot of a given
    capacity. Never donate the old table into this update — its buffers
    are exactly what an in-flight snapshot still reads."""
    return dataclasses.replace(
        table,
        v_shaped=table.v_shaped.at[slot].set(shaped.v_shaped),
        joints=table.joints.at[slot].set(shaped.joints),
        shape=table.shape.at[slot].set(shaped.shape),
    )


def table_grow(table: SubjectTable, capacity: int) -> SubjectTable:
    """Grow the per-subject leaves to ``capacity`` (zero-filled tail).

    The doubling schedule lives in the CALLER (serving engine); this is
    the mechanism. Shrinking is refused — rows would silently vanish.
    """
    pad = capacity - table.capacity
    if pad < 0:
        raise ValueError(
            f"cannot shrink a subject table from {table.capacity} "
            f"to {capacity} rows")
    if pad == 0:
        return table

    def grow(leaf):
        return jnp.concatenate(
            [leaf, jnp.zeros((pad, *leaf.shape[1:]), leaf.dtype)])

    return dataclasses.replace(
        table,
        v_shaped=grow(table.v_shaped),
        joints=grow(table.joints),
        shape=grow(table.shape),
    )


def table_row(table: SubjectTable, slot: int) -> ShapedHand:
    """Read one subject back out as a :class:`ShapedHand` (shared leaves
    referenced, not copied) — the inverse of ``table_set_row``."""
    return ShapedHand(
        v_shaped=table.v_shaped[slot],
        joints=table.joints[slot],
        shape=table.shape[slot],
        pose_basis=table.pose_basis,
        lbs_weights=table.lbs_weights,
        parents=table.parents,
    )


def forward_posed_gather(
    table: SubjectTable,
    subject_idx: jnp.ndarray,  # [B] int32 row indices into the table
    pose: jnp.ndarray,         # [B, J, 3]
    precision=DEFAULT_PRECISION,
    compute_dtype=None,
) -> ManoOutput:
    """Mixed-subject pose-only forward: row ``r`` runs the pose stage
    over subject ``subject_idx[r]``'s baked shape constants, gathered
    from the table INSIDE the jitted program.

    This is what turns the subject from a per-batch executable constant
    into a per-row runtime index (the PR-4 coalescing tentpole): one
    compiled program per (capacity, batch) shape serves every mixture
    of subjects. Bit-identity contract (pinned in
    tests/test_serving_coalesce.py): at a matched batch size, row ``r``
    equals the corresponding row of
    ``forward_posed_batched(shaped_of(subject_idx[r]), pose)`` EXACTLY
    (f32 ``==``) — the shared basis leaves stay unbatched (closed over,
    so every contraction keeps the shapes of the shared-ShapedHand
    program), the gathered per-row constants enter only elementwise ops
    and per-row-batched contractions, and vmapped rows are computed
    independently, so a row's bits depend only on its own inputs.

    ``compute_dtype`` (PR 14): the serving bf16 tier — per-row pose
    stages run with bf16 contraction operands and f32 accumulation
    (see ``forward_posed``); the gather itself stays f32 data movement
    and the returned vertices are f32. NOT bit-identical to the f32
    family (~4e-4 m measured); judged against the PrecisionPolicy
    envelope by the numerics sentinel, never by f32-digest equality.
    """
    _check_compute_dtype(compute_dtype)
    n_joints = table.joints.shape[-2]
    dtype = table.v_shaped.dtype
    pose = pose.reshape(pose.shape[0], n_joints, 3).astype(dtype)
    idx = jnp.asarray(subject_idx, jnp.int32)
    v_rows = table.v_shaped[idx]
    j_rows = table.joints[idx]
    s_rows = table.shape[idx]

    def row(v_shaped, joints, shape, q):
        sh = ShapedHand(
            v_shaped=v_shaped,
            joints=joints,
            shape=shape,
            pose_basis=table.pose_basis,     # closed over: stays unbatched
            lbs_weights=table.lbs_weights,   # closed over: stays unbatched
            parents=table.parents,
        )
        return forward_posed(sh, q, precision, compute_dtype)

    return jax.vmap(row)(v_rows, j_rows, s_rows, pose)


def forward_posed_gather_fused(
    table: SubjectTable,
    subject_idx: jnp.ndarray,  # [B] int32 row indices into the table
    pose: jnp.ndarray,         # [B, J, 3]
    precision=DEFAULT_PRECISION,
    block_b: Optional[int] = None,
    interpret: bool = False,
    compute_dtype=None,
) -> jnp.ndarray:
    """Mixed-subject pose-only forward in ONE Pallas launch; verts only.

    The kernel twin of ``forward_posed_gather`` (ops/pallas_posed.py):
    the SubjectTable row gather, pose-corrective blend, FK and skinning
    all run per batch tile in VMEM — table and index stay runtime
    arguments, so one compiled program per (capacity, batch) shape
    serves every subject mixture with zero per-subject recompiles.
    Numerics are within ~1e-5 (f32) of the XLA gathered program per
    row, NOT bit-identical (the kernel's 3-pass MXU precision policy);
    the serving engine selects this tier with
    ``ServingEngine(posed_kernel="fused")``. Inference only (no VJP —
    solvers stay on XLA, the measured dead-end).

    ``compute_dtype`` (PR 14): the serving bf16 tier — bf16 selects
    the kernel's single-pass bf16 MXU form with f32 accumulation for
    the pose blend and skinning dots (the one-hot gather stays the
    exact 3-pass reconstruction; ops/pallas_posed.py).
    """
    from mano_hand_tpu.ops import pallas_posed

    if pose.shape[0] == 0:
        return jnp.zeros((0, table.n_verts, 3), table.v_shaped.dtype)
    pose = pose.reshape(pose.shape[0], -1, 3)
    bb = pallas_posed.POSED_FUSED_BEST_BLOCK_B if block_b is None \
        else block_b
    return pallas_posed.forward_posed_gather_fused(
        table, subject_idx, pose, precision,
        block_b=min(bb, pose.shape[0]), interpret=interpret,
        compute_dtype=compute_dtype,
    )


def decode_pca(
    params: ManoParams,
    pca_coeffs: jnp.ndarray,
    global_rot: Optional[jnp.ndarray] = None,
    precision=DEFAULT_PRECISION,
) -> jnp.ndarray:
    """PCA pose coefficients [n<=(J-1)*3] -> full pose [J, 3].

    Reference semantics (/root/reference/mano_np.py:66-72): truncated basis
    rows, add the mean pose, prepend the global-rotation row. The number of
    coefficients is a static property of the input shape; the articulated
    joint count comes from the asset (15 for MANO, 23 for SMPL bodies,
    whose synthesized identity basis makes this a pass-through).
    """
    n = pca_coeffs.shape[-1]
    flat = (
        jnp.einsum("...n,nf->...f", pca_coeffs, params.pca_basis[:n],
                   precision=precision)
        + params.pca_mean
    )
    n_arti = params.pca_mean.shape[-1] // 3
    fingers = flat.reshape(*pca_coeffs.shape[:-1], n_arti, 3)
    root_shape = (*pca_coeffs.shape[:-1], 1, 3)
    if global_rot is None:
        root = jnp.zeros(root_shape, dtype=fingers.dtype)
    else:
        root = jnp.asarray(global_rot, dtype=fingers.dtype)
        if root.ndim <= 1:
            # A single [3] rotation broadcasts across any coefficient batch.
            root = jnp.broadcast_to(root.reshape(3), root_shape)
        else:
            root = root.reshape(root_shape)
    return jnp.concatenate([root, fingers], axis=-2)


def forward(
    params: ManoParams,
    pose: Optional[jnp.ndarray] = None,   # [J, 3] axis-angle, row 0 global
    shape: Optional[jnp.ndarray] = None,  # [S]
    precision=DEFAULT_PRECISION,
) -> ManoOutput:
    """Single-hand forward pass. Batch with jax.vmap over (pose, shape)."""
    n_joints = params.j_regressor.shape[0]
    dtype = params.v_template.dtype
    if pose is None:
        pose = jnp.zeros((n_joints, 3), dtype=dtype)
    pose = pose.reshape(n_joints, 3).astype(dtype)
    return forward_rotmats(
        params, ops.rotation_matrix(pose), shape, precision
    )


def fused_blend_bases(params: ManoParams, precision=DEFAULT_PRECISION):
    """Per-asset derived tensors for the fused forward path.

    Returns (vertex_basis [V*3, S+P], joint_template [J, 3],
    joint_shape_basis [J, 3, S]). Exploits linearity: since joints are an
    affine map of the shaped template (mano_np.py:81-83), Jreg can be
    precomposed with the shape basis, and the shape + pose-corrective
    blendshapes concatenate into ONE [V*3, S+P] matrix — a single
    MXU-shaped matmul per eval instead of two skinny contractions. All
    three are batch-invariant, so XLA hoists them out of vmapped programs.
    """
    v, _, s = params.shape_basis.shape
    pdim = params.pose_basis.shape[-1]
    vertex_basis = jnp.concatenate(
        [
            params.shape_basis.reshape(v * 3, s),
            params.pose_basis.reshape(v * 3, pdim),
        ],
        axis=1,
    )
    joint_template = jnp.einsum(
        "jv,vc->jc", params.j_regressor, params.v_template,
        precision=precision,
    )
    joint_shape_basis = jnp.einsum(
        "jv,vcs->jcs", params.j_regressor, params.shape_basis,
        precision=precision,
    )
    return vertex_basis, joint_template, joint_shape_basis


def forward_fused(
    params: ManoParams,
    pose: Optional[jnp.ndarray] = None,
    shape: Optional[jnp.ndarray] = None,
    precision=DEFAULT_PRECISION,
) -> ManoOutput:
    """Forward pass with fused blendshape/joint contractions.

    Numerically equivalent to ``forward`` (exact in real arithmetic; within
    f32 rounding in practice) with better MXU utilization: one
    [S+P]-coefficient matmul drives all vertex displacement, and joint
    regression shrinks to a [J,3,S]·[S] contraction.
    """
    n_joints = params.j_regressor.shape[0]
    dtype = params.v_template.dtype
    if pose is None:
        pose = jnp.zeros((n_joints, 3), dtype=dtype)
    pose = pose.reshape(n_joints, 3).astype(dtype)
    return forward_fused_rotmats(
        params, ops.rotation_matrix(pose), shape, precision
    )


def forward_fused_rotmats(
    params: ManoParams,
    rot_mats: jnp.ndarray,   # [J, 3, 3] per-joint rotations, row 0 global
    shape: Optional[jnp.ndarray] = None,
    precision=DEFAULT_PRECISION,
) -> ManoOutput:
    """Fused-basis forward from rotation MATRICES (``forward_fused`` minus
    Rodrigues — see ``forward_rotmats`` for the input contract)."""
    n_joints = params.j_regressor.shape[0]
    dtype = params.v_template.dtype
    if shape is None:
        shape = jnp.zeros((params.shape_basis.shape[-1],), dtype=dtype)
    rot_mats = rot_mats.reshape(n_joints, 3, 3).astype(dtype)
    shape = shape.astype(dtype)

    vertex_basis, joint_template, joint_shape_basis = fused_blend_bases(
        params, precision
    )
    eye = jnp.eye(3, dtype=rot_mats.dtype)
    coeff = jnp.concatenate([shape, (rot_mats[1:] - eye).reshape(-1)])
    v_posed = (
        params.v_template.reshape(-1)
        + jnp.einsum("rk,k->r", vertex_basis, coeff, precision=precision)
    ).reshape(-1, 3)
    joints = joint_template + jnp.einsum(
        "jcs,s->jc", joint_shape_basis, shape, precision=precision
    )
    world_rot, world_t = ops.forward_kinematics(
        params.parents, rot_mats, joints, precision
    )
    skin_rot, skin_t = ops.skinning_transforms(
        world_rot, world_t, joints, precision
    )
    verts = ops.skin(params.lbs_weights, skin_rot, skin_t, v_posed, precision)
    return ManoOutput(
        verts=verts,
        joints=joints,
        rest_verts=v_posed,
        rot_mats=rot_mats,
        posed_joints=world_t,
    )


def forward_rotmats(
    params: ManoParams,
    rot_mats: jnp.ndarray,   # [J, 3, 3] per-joint rotations, row 0 global
    shape: Optional[jnp.ndarray] = None,  # [S]
    precision=DEFAULT_PRECISION,
) -> ManoOutput:
    """Forward pass from per-joint rotation MATRICES, skipping Rodrigues.

    The smplx-style ``pose2rot=False`` entry point: pipelines that optimize
    in rotation space (the 6D representation via ``ops.matrix_from_6d``,
    pose transfer from rotation-matrix sources) feed SO(3) elements
    directly. Matrices are used as given — no orthonormalization is
    applied, matching the reference's implicit contract that ``R`` drives
    both the pose corrective (mano_np.py:87-91) and FK (mano_np.py:96-104).
    Batch with ``jax.vmap`` over (rot_mats, shape).
    """
    n_joints = params.j_regressor.shape[0]
    dtype = params.v_template.dtype
    if shape is None:
        shape = jnp.zeros((params.shape_basis.shape[-1],), dtype=dtype)
    rot_mats = rot_mats.reshape(n_joints, 3, 3).astype(dtype)
    shape = shape.astype(dtype)

    v_shaped = ops.shape_blend(
        params.v_template, params.shape_basis, shape, precision
    )
    joints = ops.regress_joints(params.j_regressor, v_shaped, precision)
    v_posed = ops.pose_blend(v_shaped, params.pose_basis, rot_mats, precision)
    world_rot, world_t = ops.forward_kinematics(
        params.parents, rot_mats, joints, precision
    )
    skin_rot, skin_t = ops.skinning_transforms(
        world_rot, world_t, joints, precision
    )
    verts = ops.skin(params.lbs_weights, skin_rot, skin_t, v_posed, precision)
    return ManoOutput(
        verts=verts,
        joints=joints,
        rest_verts=v_posed,
        rot_mats=rot_mats,
        posed_joints=world_t,
    )


def forward_batched_rotmats(
    params: ManoParams,
    rot_mats: jnp.ndarray,   # [B, J, 3, 3]
    shape: jnp.ndarray,      # [B, S]
    precision=DEFAULT_PRECISION,
    fused: bool = True,
) -> ManoOutput:
    """vmap over the batch axis from rotation matrices; like
    ``forward_batched``, the fused-basis path is the default (one
    [B, S+P] x [S+P, V*3] MXU matmul drives the batch's blendshapes)."""
    fwd = forward_fused_rotmats if fused else forward_rotmats
    return jax.vmap(
        lambda r, s: fwd(params, r, s, precision)
    )(rot_mats, shape)


def forward_pca(
    params: ManoParams,
    pca_coeffs: jnp.ndarray,
    global_rot: Optional[jnp.ndarray] = None,
    shape: Optional[jnp.ndarray] = None,
    precision=DEFAULT_PRECISION,
) -> ManoOutput:
    """Forward pass from PCA pose coefficients (reference's default input)."""
    pose = decode_pca(params, pca_coeffs, global_rot, precision)
    return forward(params, pose, shape, precision)


def forward_batched(
    params: ManoParams,
    pose: jnp.ndarray,   # [B, J, 3] or [B, J*3]
    shape: jnp.ndarray,  # [B, S]
    precision=DEFAULT_PRECISION,
    fused: bool = True,
) -> ManoOutput:
    """vmap over the batch axis; params replicated (closed over).

    Uses the fused-basis path by default (one [B, S+P] x [S+P, V*3] MXU
    matmul across the batch); ``fused=False`` selects the
    reference-structured staging for debugging/parity work.
    """
    fwd = forward_fused if fused else forward
    return jax.vmap(
        lambda p, s: fwd(params, p, s, precision)
    )(pose, shape)


def sample_poses(
    params: ManoParams,
    key,                     # jax PRNG key
    n: int,
    pca_scale: float = 1.0,
    global_rot_scale: float = 0.0,
    component_vars: Optional[jnp.ndarray] = None,
    precision=DEFAULT_PRECISION,
) -> jnp.ndarray:
    """Draw ``n`` anatomically plausible random poses [n, J, 3].

    Samples PCA coefficients ``z ~ N(0, pca_scale^2 I)`` (optionally
    scaled per component by ``component_vars``, e.g. from
    ``fitting.pose_component_variances`` over scan poses) and decodes
    through the asset's basis + MEAN pose — the distribution the model
    was built from (/root/reference/dump_model.py:24-43 is the
    reference's implicit version of this: scan poses ARE decoded
    coefficients). Unlike raw axis-angle noise, samples bend joints
    along directions real hands use — the right prior for synthetic
    training data (examples/11, ``keypoints_chunked``) and for
    randomized fitting restarts. ``global_rot_scale > 0`` adds a random
    axis-angle global rotation row.
    """
    k1, k2 = jax.random.split(jnp.asarray(key))
    n_pca = params.pca_mean.shape[-1]
    dtype = params.v_template.dtype
    z = jax.random.normal(k1, (n, n_pca), dtype) * pca_scale
    if component_vars is not None:
        z = z * jnp.sqrt(jnp.asarray(component_vars, dtype))
    global_rot = None
    if global_rot_scale:
        global_rot = (
            jax.random.normal(k2, (n, 3), dtype) * global_rot_scale
        )
    return decode_pca(params, z, global_rot, precision)


# ------------------------------------------------------------- keypoints
def resolve_tip_ids(tip_vertex_ids, n_verts: int):
    """Normalize a fingertip-vertex spec to a tuple of valid vertex ids.

    ``tip_vertex_ids`` is ``None`` (no tips — the bare 16 skeleton
    joints), a convention name from ``constants.TIP_VERTEX_IDS``
    (``"smplx"`` | ``"manopth"``, vertex ids on the official 778-vertex
    mesh), or an explicit sequence of vertex indices (any length — e.g.
    custom markers on a personalized mesh).
    """
    if tip_vertex_ids is None:
        return None
    if isinstance(tip_vertex_ids, str):
        try:
            tip_vertex_ids = constants.TIP_VERTEX_IDS[tip_vertex_ids]
        except KeyError:
            raise ValueError(
                f"unknown tip convention {tip_vertex_ids!r}; known: "
                f"{sorted(constants.TIP_VERTEX_IDS)} (or pass explicit "
                "vertex ids)"
            ) from None
    ids = tuple(int(i) for i in tip_vertex_ids)
    if not ids:
        return None  # () means the same as None: the bare skeleton
    bad = [i for i in ids if not 0 <= i < n_verts]
    if bad:
        raise ValueError(
            f"tip vertex ids {bad} out of range for a {n_verts}-vertex mesh"
        )
    return ids


def select_keypoints(
    verts: jnp.ndarray,
    posed_joints: jnp.ndarray,
    tips=None,                 # PRE-RESOLVED tuple (resolve_tip_ids) or None
    order: str = "mano",
    axis: int = -2,            # the keypoint/vertex axis of both inputs
) -> jnp.ndarray:
    """THE keypoint selection: concat tip rows, apply dataset ordering.

    One implementation shared by ``keypoints`` (values), the LM row
    builder, and the analytic Jacobian — which applies the SAME selection
    to Jacobian rows via ``axis=0`` (rows of [K, 3, P] tensors select in
    lockstep with the keypoints they differentiate).
    """
    if order not in ("mano", "openpose"):
        raise ValueError(f"order must be 'mano' or 'openpose', got {order!r}")
    kp = posed_joints
    if tips is not None:
        kp = jnp.concatenate(
            [kp, jnp.take(verts, jnp.array(tips), axis=axis)], axis=axis
        )
    if order == "openpose":
        n = kp.shape[axis]
        if n != len(constants.MANO21_TO_OPENPOSE):
            raise ValueError(
                "order='openpose' needs the 21-keypoint set (16 joints + "
                f"5 tips), got {n} keypoints"
            )
        kp = jnp.take(
            kp, jnp.array(constants.MANO21_TO_OPENPOSE), axis=axis
        )
    return kp


def keypoints(
    out: ManoOutput,
    tip_vertex_ids=None,
    order: str = "mano",
) -> jnp.ndarray:
    """Keypoints [..., 16(+T), 3]: posed joints + fingertip vertex picks.

    MANO's skeleton has no fingertips (the reference exposes only the 16
    FK joints, /root/reference/mano_np.py:83,96-104); datasets and
    detectors use 21 keypoints with tips taken as mesh vertices. With the
    standard 5 tips, ``order="openpose"`` re-orders into the
    OpenPose/FreiHAND convention (``constants.MANO21_TO_OPENPOSE``);
    ``order="mano"`` keeps [16 joints | tips as given]. Works on batched
    outputs (leading axes broadcast).
    """
    tips = resolve_tip_ids(tip_vertex_ids, out.verts.shape[-2])
    return select_keypoints(out.verts, out.posed_joints, tips, order)


# The bench block-size sweep's winning tile for the fused skinning kernel
# on TPU v5e (docs/benchmarking.md). THE one definition — the kernel entry
# points below and bench.py's quick sweep/fallback all read it, so a new
# sweep winner is a one-line change.
PALLAS_BEST_BLOCK = (64, 896)

# Batch tile for the fully-fused forward kernel (ops/pallas_forward.py),
# which has no vertex-tile knob (the whole padded mesh rides the lanes).
# Same contract as PALLAS_BEST_BLOCK: bench sweep winners land here.
FUSED_BEST_BLOCK_B = 128

# Batch tile for the FULL-fusion kernel (Rodrigues + FK + blend + skin in
# one launch, ops/pallas_forward.py:forward_verts_fused_full). The small
# tile wins on v5e: measured 19.6M evals/s at 64 vs 11.8M at 128 at
# launch 8192 (more grid steps, but each tile's nine [TB, J] skin dots
# stay resident-friendly; 512 exceeds the 16M scoped-vmem limit).
FUSED_FULL_BEST_BLOCK_B = 64


def forward_batched_pallas(
    params: ManoParams,
    pose: jnp.ndarray,   # [B, J, 3]
    shape: jnp.ndarray,  # [B, S]
    precision=DEFAULT_PRECISION,
    block_b: int = PALLAS_BEST_BLOCK[0],
    block_v: int = PALLAS_BEST_BLOCK[1],
    interpret: bool = False,
) -> jnp.ndarray:
    """Batched forward with the Pallas fused-LBS kernel; returns verts only.

    The pre-skinning stages (blendshapes, Rodrigues, FK) are the vmapped
    XLA path; skinning runs in one Pallas kernel that keeps the per-vertex
    blended rotations in VMEM (see ops/pallas_lbs.py). Differentiable:
    skinning carries a custom VJP whose vertex cotangent reuses the same
    kernel, so jax.grad works end-to-end through this path.
    """
    from mano_hand_tpu.ops import pallas_lbs

    def pre(p, s):
        v_shaped = ops.shape_blend(
            params.v_template, params.shape_basis, s, precision
        )
        joints = ops.regress_joints(params.j_regressor, v_shaped, precision)
        rot_mats = ops.rotation_matrix(p)
        v_posed = ops.pose_blend(
            v_shaped, params.pose_basis, rot_mats, precision
        )
        world_rot, world_t = ops.forward_kinematics(
            params.parents, rot_mats, joints, precision
        )
        skin_rot, skin_t = ops.skinning_transforms(
            world_rot, world_t, joints, precision
        )
        return skin_rot, skin_t, v_posed

    dtype = params.v_template.dtype
    if pose.shape[0] == 0:
        # Static empty batch: the kernel's grid math divides by B.
        return jnp.zeros((0, params.v_template.shape[0], 3), dtype)
    pose = pose.reshape(pose.shape[0], -1, 3).astype(dtype)
    skin_rot, skin_t, v_posed = jax.vmap(pre)(pose, shape.astype(dtype))
    # Positional call: custom_vjp functions reject keyword arguments.
    return pallas_lbs.skin_batched_ad(
        params.lbs_weights, skin_rot, skin_t, v_posed,
        block_b, block_v, interpret, precision,
    )


def forward_batched_pallas_fused(
    params: ManoParams,
    pose: jnp.ndarray,   # [B, J, 3]
    shape: jnp.ndarray,  # [B, S]
    precision=DEFAULT_PRECISION,
    block_b: int = FUSED_BEST_BLOCK_B,
    interpret: bool = False,
) -> jnp.ndarray:
    """Batched forward via the fully-fused Pallas kernel; returns verts only.

    One kernel launch covers blendshapes AND skinning (ops/pallas_forward.py)
    — the blended vertices never round-trip through HBM between the two,
    unlike ``forward_batched_pallas`` where v_posed crosses a program
    boundary. Differentiable (hybrid custom VJP).
    """
    from mano_hand_tpu.ops import pallas_forward

    # Positional call: custom_vjp functions reject keyword arguments.
    return pallas_forward.forward_verts_fused_ad(
        params, pose, shape, precision, block_b, interpret
    )


def forward_batched_pallas_fused_full(
    params: ManoParams,
    pose: jnp.ndarray,   # [B, J, 3]
    shape: jnp.ndarray,  # [B, S]
    precision=DEFAULT_PRECISION,
    block_b: int = FUSED_FULL_BEST_BLOCK_B,
    interpret: bool = False,
    stack_skin=False,  # False | True (4-way) | "full" (12-way)
) -> jnp.ndarray:
    """Batched forward with the WHOLE pipeline in one Pallas launch.

    Rodrigues, shaped-joint regression, level-parallel FK, inverse-bind,
    blendshapes and skinning all run per batch tile in VMEM
    (ops/pallas_forward.py:forward_verts_fused_full) — no XLA pre-stage,
    no r/t slab HBM round-trips. Inputs are just (pose, shape); returns
    verts only. Differentiable via the shared hybrid VJP. Any
    topologically ordered kinematic tree lays out (level_layout splits
    BFS levels into parent-aligned segments).
    """
    from mano_hand_tpu.ops import pallas_forward

    if pose.shape[0] == 0:
        return jnp.zeros((0, params.v_template.shape[0], 3),
                         params.v_template.dtype)
    pose = pose.reshape(pose.shape[0], -1, 3)
    # Positional call: custom_vjp functions reject keyword arguments.
    return pallas_forward.forward_verts_fused_full_ad(
        params, pose, shape, precision, block_b, interpret, stack_skin
    )


def forward_hands_pallas_fused_full(
    stacked: ManoParams,     # stack_params output, [2, ...] leaves
    pose: jnp.ndarray,       # [2, B, J, 3]
    shape: jnp.ndarray,      # [2, B, S]
    precision=DEFAULT_PRECISION,
    block_b: int = FUSED_FULL_BEST_BLOCK_B,
    interpret: bool = False,
    stack_skin=False,  # False | True (4-way) | "full" (12-way)
) -> jnp.ndarray:
    """Both hands' full-fusion forward in ONE kernel launch: [2, B, V, 3].

    The single-launch counterpart of ``forward_hands`` for the kernel
    path: the grid runs hand-major over (hand, batch-tile), so the
    two-hand workload of BASELINE configs 3/5 pays one launch instead of
    two sequenced ones (ops/pallas_forward.py:
    forward_verts_fused_full_hands). Inference path (no custom VJP —
    fitting stays on the XLA solvers, docs/roadmap.md dead-end #2).
    """
    from mano_hand_tpu.ops import pallas_forward

    return pallas_forward.forward_verts_fused_full_hands(
        stacked, pose, shape, precision, block_b=block_b,
        interpret=interpret, stack_skin=stack_skin,
    )


def stack_params(left: ManoParams, right: ManoParams) -> ManoParams:
    """Stack a (left, right) asset pair into one PyTree with [2, ...] leaves.

    The reference ships hands as two separate asset files
    (/root/reference/dump_model.py:48-49) and evaluates them in separate
    calls; stacking lets ``forward_hands`` vmap over the hand axis so a
    two-hand workload is ONE XLA program with hand-batched matmuls.
    ``side`` becomes "stacked" (do not pass to schema.validate); parents
    must match (they always do for MANO).
    """
    import dataclasses

    if tuple(left.parents) != tuple(right.parents):
        raise ValueError("cannot stack params with different kinematic trees")
    right_aligned = dataclasses.replace(right, side=left.side)
    stacked = jax.tree_util.tree_map(
        lambda a, b: jnp.stack([jnp.asarray(a), jnp.asarray(b)]),
        left, right_aligned,
    )
    return dataclasses.replace(stacked, side="stacked")


def forward_hands(
    stacked: ManoParams,     # stack_params output, [H, ...] leaves
    pose: jnp.ndarray,       # [H, B, J, 3]
    shape: jnp.ndarray,      # [H, B, S]
    precision=DEFAULT_PRECISION,
) -> ManoOutput:
    """Multi-hand batched forward: vmap over the hand axis of params AND
    inputs — one program, hand-major outputs [H, B, ...]."""
    return jax.vmap(
        lambda prm, p, s: forward_batched(prm, p, s, precision)
    )(stacked, pose, shape)


def forward_chunked(
    params: ManoParams,
    pose: jnp.ndarray,
    shape: jnp.ndarray,
    chunk_size: int = 8192,
    precision=DEFAULT_PRECISION,
    use_pallas: bool = False,
    block_b: Optional[int] = None,
    block_v: int = PALLAS_BEST_BLOCK[1],
    interpret: bool = False,
    use_pallas_fused: bool = False,
    use_pallas_fused_full: bool = False,
    stack_skin=False,  # False | True (4-way) | "full" (12-way)
) -> jnp.ndarray:
    """Memory-bounded huge-batch vertices via lax.map over chunks.

    Keeps the per-chunk [chunk, V, 3, 3] LBS intermediate under ~2 GB while
    the MXU stays saturated; returns verts only ([B, V, 3]). Any batch size
    works: a trailing partial chunk is zero-padded internally (static pad,
    jit-safe) and the padding sliced off the output. ``use_pallas`` routes
    each chunk's skinning through the fused Pallas skinning kernel;
    ``use_pallas_fused`` routes the whole vertex path (blend + skin) through
    the fully-fused kernel (ops/pallas_forward.py), where ``block_b`` is its
    batch tile; ``use_pallas_fused_full`` routes the ENTIRE forward
    (Rodrigues + FK included) through the full-fusion kernel; its
    ``stack_skin`` batches the skinny skin dots (full-fusion route only).
    Block defaults are the bench sweep's winners (docs/benchmarking.md).
    """
    b = pose.shape[0]
    pose_c, shape_c, chunk_size = _pad_and_chunk(pose, shape, chunk_size)
    if use_pallas_fused_full:
        # Each kernel route defaults to ITS OWN swept tile, not the other's.
        bb = FUSED_FULL_BEST_BLOCK_B if block_b is None else block_b
        chunk_fn = lambda ps: forward_batched_pallas_fused_full(  # noqa: E731
            params, ps[0], ps[1], precision,
            block_b=min(bb, chunk_size), interpret=interpret,
            stack_skin=stack_skin,
        )
    elif use_pallas_fused:
        bb = FUSED_BEST_BLOCK_B if block_b is None else block_b
        chunk_fn = lambda ps: forward_batched_pallas_fused(  # noqa: E731
            params, ps[0], ps[1], precision,
            block_b=min(bb, chunk_size), interpret=interpret,
        )
    elif use_pallas:
        bb = PALLAS_BEST_BLOCK[0] if block_b is None else block_b
        chunk_fn = lambda ps: forward_batched_pallas(  # noqa: E731
            params, ps[0], ps[1], precision,
            block_b=min(bb, chunk_size), block_v=block_v,
            interpret=interpret,
        )
    else:
        chunk_fn = lambda ps: forward_batched(  # noqa: E731
            params, ps[0], ps[1], precision
        ).verts
    verts = jax.lax.map(chunk_fn, (pose_c, shape_c))
    return verts.reshape(-1, *verts.shape[2:])[:b]


def _pad_and_chunk(pose, shape, chunk_size):
    """Zero-pad the batch to a chunk multiple and reshape to
    [n_chunks, chunk, ...] — the shared scaffolding of every chunked
    evaluator (static pad, jit-safe)."""
    b = pose.shape[0]
    chunk_size = max(1, min(chunk_size, b))  # max(1,..) keeps B=0 legal
    pad = (-b) % chunk_size
    if pad:
        pose = jnp.concatenate(
            [pose, jnp.zeros((pad, *pose.shape[1:]), pose.dtype)]
        )
        shape = jnp.concatenate(
            [shape, jnp.zeros((pad, *shape.shape[1:]), shape.dtype)]
        )
    n_chunks = (b + pad) // chunk_size
    return (
        pose.reshape(n_chunks, chunk_size, *pose.shape[1:]),
        shape.reshape(n_chunks, chunk_size, *shape.shape[1:]),
        chunk_size,
    )


def keypoints_chunked(
    params: ManoParams,
    pose: jnp.ndarray,     # [B, J, 3]
    shape: jnp.ndarray,    # [B, S]
    tip_vertex_ids=None,
    order: str = "mano",
    chunk_size: int = 8192,
    precision=DEFAULT_PRECISION,
) -> jnp.ndarray:
    """Huge-batch keypoints [B, 16(+T), 3] without a [B, V, 3] vertex slab.

    The synthetic-data-factory path: generating 21-keypoint labels for
    millions of poses (e.g. to train a neural regressor, examples/11)
    needs only the [B, K, 3] keypoints — 250 MB at B=1M versus 9.3 GB of
    vertices. Chunks evaluate through the fused-basis forward and reduce
    to keypoints in-chunk, so full-mesh vertices never accumulate across
    the batch.
    """
    b = pose.shape[0]
    tips = resolve_tip_ids(tip_vertex_ids, params.v_template.shape[-2])
    pose_c, shape_c, _ = _pad_and_chunk(pose, shape, chunk_size)

    def chunk_fn(ps):
        out = forward_batched(params, ps[0], ps[1], precision)
        return select_keypoints(out.verts, out.posed_joints, tips, order)

    kp = jax.lax.map(chunk_fn, (pose_c, shape_c))
    return kp.reshape(-1, *kp.shape[2:])[:b]


@functools.partial(jax.jit, static_argnames=("precision",))
def jit_forward(params, pose, shape, precision=DEFAULT_PRECISION):
    """Convenience jitted single-hand forward."""
    return forward(params, pose, shape, precision)


@functools.partial(jax.jit, static_argnames=("precision",))
def jit_forward_batched(params, pose, shape, precision=DEFAULT_PRECISION):
    """Convenience jitted batched forward."""
    return forward_batched(params, pose, shape, precision)


@functools.partial(jax.jit, static_argnames=("precision",))
def jit_forward_rotmats(params, rot_mats, shape,
                        precision=DEFAULT_PRECISION):
    """Convenience jitted single-hand rotation-matrix forward."""
    return forward_rotmats(params, rot_mats, shape, precision)


@functools.partial(jax.jit, static_argnames=("precision",))
def jit_specialize(params, shape, precision=DEFAULT_PRECISION):
    """Convenience jitted shape-stage bake (params ride as runtime
    arguments, like every jitted entry here — constant-baking would
    change float folding and break the bit-identity contract)."""
    return specialize(params, shape, precision)


@functools.partial(jax.jit, static_argnames=("precision",))
def jit_forward_posed(shaped, pose, precision=DEFAULT_PRECISION):
    """Convenience jitted single-hand pose-only forward."""
    return forward_posed(shaped, pose, precision)


@functools.partial(jax.jit, static_argnames=("precision",))
def jit_forward_posed_batched(shaped, pose, precision=DEFAULT_PRECISION):
    """Convenience jitted batched pose-only forward."""
    return forward_posed_batched(shaped, pose, precision)


@functools.partial(jax.jit, static_argnames=("precision",))
def jit_forward_batched_rotmats(params, rot_mats, shape,
                                precision=DEFAULT_PRECISION):
    """Convenience jitted batched rotation-matrix forward."""
    return forward_batched_rotmats(params, rot_mats, shape, precision)


@functools.partial(jax.jit, static_argnames=("precision",))
def jit_forward_posed_gather(table, subject_idx, pose,
                             precision=DEFAULT_PRECISION):
    """Convenience jitted mixed-subject gathered pose-only forward (table
    and index ride as runtime arguments — one program per
    (capacity, batch) shape, shared by every subject mixture)."""
    return forward_posed_gather(table, subject_idx, pose, precision)


@functools.partial(jax.jit,
                   static_argnames=("precision", "block_b", "interpret"))
def jit_forward_posed_gather_fused(table, subject_idx, pose,
                                   precision=DEFAULT_PRECISION,
                                   block_b=None, interpret=False):
    """Convenience jitted FUSED gathered pose-only forward (verts only;
    table and index ride as runtime arguments, like the XLA twin)."""
    return forward_posed_gather_fused(table, subject_idx, pose,
                                      precision, block_b, interpret)


# One compiled row-update program per table capacity (``slot`` is traced,
# so writing row 7 and row 12 reuse the same executable). Deliberately
# NOT donated: the old table's buffers are what in-flight dispatch
# snapshots still read (see table_set_row).
jit_table_set_row = jax.jit(table_set_row)
