"""Stateful MANOModel wrapper: the reference's ergonomics over the pure core.

Preserves the reference API and its quirks (/root/reference/mano_np.py:48-77):

  * ``set_params(pose_abs | pose_pca, shape, global_rot)`` mutates state and
    returns a copy of the vertices;
  * ``global_rot`` is honored **only** in the PCA branch (mano_np.py:70-72),
    and persists across calls (``self.rot`` is stateful);
  * a freshly constructed model already holds the rest-pose mesh
    (``update()`` runs in ``__init__``, mano_np.py:46);
  * exposed attributes: ``verts``, ``rest_verts``, ``J``, ``R``, ``faces``.

The backend flag (``np`` | ``jax``) selects the float64 oracle or the jitted
TPU core per call — the contract named in BASELINE.json's north star. The
mutable state lives out here; the jitted core stays pure.
"""

from __future__ import annotations

from pathlib import Path
from typing import Optional, Union

import jax
import jax.numpy as jnp
import numpy as np

from mano_hand_tpu import constants
from mano_hand_tpu.assets.loader import load_model
from mano_hand_tpu.assets.schema import ManoParams
from mano_hand_tpu.io.obj import export_obj_pair
from mano_hand_tpu.models import core, oracle

BACKENDS = ("np", "jax")


class MANOModel:
    """Drop-in replacement for the reference's MANOModel class."""

    def __init__(
        self,
        model: Union[str, Path, ManoParams],
        backend: str = "jax",
        dtype=jnp.float32,
    ):
        if isinstance(model, (str, Path)):
            model = load_model(model)
        self._params_np = model  # float64 master copy (oracle path)
        self._dtype = np.dtype(dtype)
        self._params_jax_cache = None  # built lazily: the np backend must
        # work without touching any JAX device (e.g. accelerator offline)
        self._bucket_exes = {}  # bucket -> compiled forward (forward_bucketed)
        self.serving_counters = None  # built with the first bucketed call
        self._shaped_cache = None  # (betas_bytes, core.ShapedHand): the
        # wrapper's specialization cache — set_params holds betas fixed
        # across calls (reference usage: per-frame pose updates on one
        # subject), so the jax path re-runs only the pose stage then.
        self.backend = self._check_backend(backend)

        self.n_joints = model.n_joints
        self.n_shape_params = model.n_shape
        self.faces = np.asarray(model.faces)
        self.side = model.side

        # Reference state layout (mano_np.py:38-44).
        self.pose = np.zeros((self.n_joints, 3))
        self.shape = np.zeros(self.n_shape_params)
        self.rot = np.zeros((1, 3))
        self.verts = None
        self.rest_verts = None
        self.J = None
        self.R = None

        self.update()

    @staticmethod
    def _check_backend(backend: str) -> str:
        if backend not in BACKENDS:
            raise ValueError(f"backend must be one of {BACKENDS}, got {backend!r}")
        return backend

    @property
    def params(self) -> ManoParams:
        """The float64 parameter PyTree (asset master copy)."""
        return self._params_np

    @property
    def _params_jax(self) -> ManoParams:
        if self._params_jax_cache is None:
            self._params_jax_cache = (
                self._params_np.astype(self._dtype).device_put()
            )
        return self._params_jax_cache

    def specialize(self, shape=None) -> "core.ShapedHand":
        """Bake betas into a ``core.ShapedHand``, cached per betas value.

        The wrapper holds ONE subject, so one live entry suffices: a
        repeat call with the same betas (every ``set_params`` that only
        moves the pose — the reference's per-frame loop,
        /root/reference/data_explore.py:12-15) returns the cached bake
        and the forward pays only the pose stage. A betas change
        replaces the entry. jax backend only (the np oracle path never
        touches a JAX device).
        """
        shape = (np.zeros(self.n_shape_params, self._dtype) if shape is None
                 else np.asarray(shape, self._dtype))
        key = shape.tobytes()
        if self._shaped_cache is None or self._shaped_cache[0] != key:
            self._shaped_cache = (
                key, core.jit_specialize(self._params_jax, jnp.asarray(shape))
            )
        return self._shaped_cache[1]

    # ------------------------------------------------------------- reference API
    def set_params(
        self,
        pose_abs=None,
        pose_pca=None,
        shape=None,
        global_rot=None,
    ) -> np.ndarray:
        """Reference semantics (mano_np.py:48-77), including the quirk that
        global_rot only takes effect through the PCA branch and persists."""
        if pose_abs is not None:
            self.pose = np.asarray(pose_abs, dtype=np.float64)
        if pose_pca is not None:
            if global_rot is not None:
                self.rot = np.asarray(global_rot, dtype=np.float64).reshape(1, 3)
            fingers = oracle.decode_pca_pose(self._params_np, pose_pca)[1:]
            self.pose = np.concatenate([self.rot, fingers], axis=0)
        if shape is not None:
            self.shape = np.asarray(shape, dtype=np.float64)
        self.update()
        return self.verts.copy()

    def update(self) -> None:
        """Recompute verts/J/R/rest_verts from current state via the
        selected backend."""
        out = self._evaluate(self.pose, self.shape, self.backend)
        self.verts = np.asarray(out.verts, dtype=np.float64)
        self.rest_verts = np.asarray(out.rest_verts, dtype=np.float64)
        self.J = np.asarray(out.joints, dtype=np.float64)
        self.R = np.asarray(out.rot_mats, dtype=np.float64)
        self.posed_J = np.asarray(out.posed_joints, dtype=np.float64)

    def export_obj(self, path: Union[str, Path]) -> None:
        """Write posed + rest-pose OBJ pair (mano_np.py:181-201 parity)."""
        export_obj_pair(self.verts, self.rest_verts, self.faces, path)

    def export_ply(
        self, path: Union[str, Path],
        with_normals: bool = True, binary: bool = True,
    ) -> None:
        """Write the posed mesh as PLY (binary by default; beyond the
        reference, which only speaks OBJ). Normals are the area-weighted
        vertex normals of the current pose, computed in NumPy so the np
        backend's no-JAX-device contract (see __init__) holds here too."""
        from mano_hand_tpu.io.ply import export_ply, vertex_normals_np

        normals = (
            vertex_normals_np(self.verts, self.faces)
            if with_normals else None
        )
        export_ply(self.verts, self.faces, path,
                   normals=normals, binary=binary)

    def fit(self, target, solver: str = "adam",
            deadline_s: Optional[float] = None, retries: int = 0,
            **solver_kw):
        """Recover pose/shape from a target and ADOPT the solution.

        The stateful counterpart of ``fitting.fit``/``fitting.fit_lm``:
        one call fits a SINGLE problem (this wrapper holds one hand's
        state), writes the recovered pose/shape into the model, runs
        ``update()``, and returns the solver result. Any library data
        term and option passes through ``solver_kw`` (data_term, camera,
        priors, ...). ``fit_trans`` is refused — the wrapper, like the
        reference, keeps the hand origin-centered and has no translation
        state; use the functional API when fitting placement.

        ``deadline_s``/``retries`` opt the solve into SUPERVISED
        execution (``runtime.supervise.supervised_call``): a long fit
        against a tunneled device can wedge inside a C-level RPC that
        no signal clears — supervised, the blocked solve is abandoned
        at the deadline (``DeadlineExceeded`` -> bounded retries ->
        ``RetriesExhausted``), and the model's state stays untouched on
        failure. Deterministic solver errors (bad shapes, bad options)
        are never retried. Default (both unset): the plain direct call.
        """
        from mano_hand_tpu import fitting

        if solver not in ("adam", "lm"):
            raise ValueError(f"solver must be 'adam' or 'lm', got {solver!r}")
        if solver_kw.get("fit_trans"):
            raise ValueError(
                "MANOModel.fit has no translation state (the wrapper is "
                "origin-centered like the reference); use fitting.fit("
                "..., fit_trans=True) directly"
            )
        # An explicit fit_trans=False means "off" — drop it rather than
        # leak a kwarg fit_lm's signature does not have.
        solver_kw.pop("fit_trans", None)
        fn = fitting.fit if solver == "adam" else fitting.fit_lm
        if deadline_s is not None or retries:
            from mano_hand_tpu.runtime.supervise import supervised_call

            # block_until_ready INSIDE the supervised window: the solver
            # returns asynchronously-dispatched arrays, and the hang
            # being guarded against lives in the device work, not the
            # Python call.
            res = supervised_call(
                lambda: jax.block_until_ready(
                    fn(self._params_jax, target, **solver_kw)),
                deadline_s=deadline_s, retries=retries,
                name=f"model-fit-{solver}")
        else:
            res = fn(self._params_jax, target, **solver_kw)
        if np.asarray(res.pose).ndim != 2:
            raise ValueError(
                "MANOModel.fit adopts ONE solution; batched targets "
                f"produced pose shape {np.asarray(res.pose).shape} — use "
                "fitting.fit for batches"
            )
        self.pose = np.asarray(res.pose, dtype=np.float64)
        self.shape = np.asarray(res.shape, dtype=np.float64)
        self.update()
        return res

    def keypoints(self, tip_vertex_ids=None, order: str = "mano"):
        """Current-state keypoints [16(+T), 3] (float64 numpy).

        The dataset-facing joint set: the 16 posed skeleton joints,
        optionally extended with fingertip vertex picks
        (``"smplx"``/``"manopth"`` conventions or explicit vertex ids)
        and re-ordered to the OpenPose/FreiHAND convention — see
        ``models.core.keypoints``. The reference exposes only the bare
        FK joints (/root/reference/mano_np.py:83).
        """
        # Deliberately pure-numpy (not core.select_keypoints): the np
        # backend must work without initializing any JAX device.
        tips = core.resolve_tip_ids(tip_vertex_ids, self.verts.shape[0])
        kp = self.posed_J
        if tips is not None:
            kp = np.concatenate([kp, self.verts[list(tips)]], axis=0)
        if order == "openpose":
            if kp.shape[0] != len(constants.MANO21_TO_OPENPOSE):
                raise ValueError(
                    "order='openpose' needs the 21-keypoint set (16 "
                    f"joints + 5 tips), got {kp.shape[0]} keypoints"
                )
            kp = kp[list(constants.MANO21_TO_OPENPOSE)]
        elif order != "mano":
            raise ValueError(
                f"order must be 'mano' or 'openpose', got {order!r}"
            )
        return kp.copy()

    # ----------------------------------------------------------- functional API
    def __call__(
        self,
        pose: Optional[np.ndarray] = None,
        shape: Optional[np.ndarray] = None,
        pose_pca: Optional[np.ndarray] = None,
        global_rot: Optional[np.ndarray] = None,
        backend: Optional[str] = None,
    ) -> np.ndarray:
        """Stateless evaluation: verts for the given pose/shape.

        The ``backend`` flag selects ``np`` (float64 oracle) or ``jax``
        (jitted TPU core) per call, per BASELINE.json's north star. Accepts
        either absolute pose [.., 16, 3] or PCA coefficients [.., n<=45];
        leading batch dimensions are dispatched to the vmapped core (np
        backend is unbatched, like the reference).
        """
        backend = self._check_backend(backend or self.backend)
        if (pose is None) == (pose_pca is None):
            if pose is None:
                pose = np.zeros((self.n_joints, 3))
            else:
                raise ValueError("pass exactly one of pose / pose_pca")
        if global_rot is not None and pose_pca is None:
            # Absolute pose already carries the root rotation in row 0;
            # silently ignoring global_rot here would return an un-rotated
            # mesh (the reference's set_params quirk is preserved only in
            # set_params, not in this functional API).
            raise ValueError(
                "global_rot is only meaningful with pose_pca; with an "
                "absolute pose, put the root rotation in pose[..., 0, :]"
            )
        if pose_pca is not None and backend == "np" and np.ndim(pose_pca) > 1:
            raise ValueError(
                "np backend is unbatched (like the reference); "
                "use backend='jax' for batched evaluation"
            )
        if pose_pca is not None:
            if backend == "np":
                pose = oracle.decode_pca_pose(
                    self._params_np, pose_pca, global_rot
                )
            else:
                pose = core.decode_pca(
                    self._params_jax,
                    jnp.asarray(pose_pca, self._params_jax.v_template.dtype),
                    None if global_rot is None
                    else jnp.asarray(global_rot,
                                     self._params_jax.v_template.dtype),
                )
        pose = np.asarray(pose) if backend == "np" else pose
        if shape is None:
            shape = np.zeros(
                (*np.shape(pose)[:-2], self.n_shape_params)
            )
        return np.asarray(self._evaluate(pose, shape, backend).verts)

    def forward_bucketed(
        self,
        pose: np.ndarray,           # [n, J, 3], any n >= 1
        shape: Optional[np.ndarray] = None,
        *,
        min_bucket: int = 1,
        max_bucket: int = 1024,
        donate: Optional[bool] = None,
    ) -> np.ndarray:
        """Bucket-aware batched forward: verts [n, V, 3] for ANY n without
        a per-n recompile.

        The serving-shaped entry point (serving/buckets.py policy): the
        batch is padded to the nearest power-of-two bucket, runs through
        a per-bucket compiled-executable cache held on this instance,
        and the pad rows are sliced back off — so ragged request sizes
        compile ``log2(max_bucket)`` programs total instead of one per
        novel n. Inputs are donated to XLA (``donate_argnums``) on
        device backends (``donate=None`` auto-disables on CPU, where
        donation is unimplemented). Results are bit-identical to the
        direct ``__call__`` jax path at the same dtype — the pad rows
        are dead rows of an independent-per-row ``vmap``
        (tests/test_serving.py pins this). Compile/padding behaviour is
        observable on ``self.serving_counters``. For a full async
        micro-batching front end (request coalescing, AOT persistence),
        use ``serving.ServingEngine``.
        """
        from mano_hand_tpu.serving import buckets as bucket_mod
        from mano_hand_tpu.utils.profiling import ServingCounters

        if self.serving_counters is None:
            self.serving_counters = ServingCounters()
        pose = np.asarray(pose, self._dtype)
        if pose.ndim != 3 or pose.shape[1:] != (self.n_joints, 3):
            raise ValueError(
                f"forward_bucketed pose must be [n, {self.n_joints}, 3], "
                f"got {pose.shape} (single poses: use __call__)")
        n = pose.shape[0]
        if shape is None:
            shape = np.zeros((n, self.n_shape_params), self._dtype)
        else:
            shape = np.asarray(shape, self._dtype)
            if shape.shape != (n, self.n_shape_params):
                raise ValueError(
                    f"forward_bucketed shape must be "
                    f"[{n}, {self.n_shape_params}], got {shape.shape}")
        from mano_hand_tpu.serving.engine import (
            build_bucket_executable, default_donate,
        )

        sizes = bucket_mod.bucket_sizes(min_bucket, max_bucket)
        bucket = bucket_mod.bucket_for(n, sizes)
        donate = default_donate() if donate is None else bool(donate)
        # Keyed by (bucket, donate): an explicit donate flip must build
        # its own executable, not silently reuse one compiled under the
        # opposite donation policy.
        key = (bucket, donate)
        exe = self._bucket_exes.get(key)
        if exe is None:
            # THE shared per-bucket build (serving/engine.py): jit fast
            # dispatch, traced params, eager dummy-batch warm-up —
            # donation policy and warm-up protocol stay in lockstep with
            # the engine by construction.
            exe = build_bucket_executable(
                self._params_jax, bucket, self.n_joints,
                self.n_shape_params, self._dtype, donate=donate,
            )
            self._bucket_exes[key] = exe
            self.serving_counters.count_compile()
        out = exe(bucket_mod.pad_rows(pose, bucket),
                  bucket_mod.pad_rows(shape, bucket))
        self.serving_counters.count_dispatch(bucket, n)
        return np.asarray(out)[:n]

    def _evaluate(self, pose, shape, backend: str):
        if backend == "np":
            if np.ndim(pose) > 2:
                raise ValueError(
                    "np backend is unbatched (like the reference); "
                    "use backend='jax' for batched evaluation"
                )
            return oracle.forward(self._params_np, pose=pose, shape=shape)
        dtype = self._params_jax.v_template.dtype
        pose_j = jnp.asarray(pose, dtype)
        shape_j = jnp.asarray(shape, dtype)
        if pose_j.ndim > 2:
            lead = pose_j.shape[:-2]
            out = core.jit_forward_batched(
                self._params_jax,
                pose_j.reshape(-1, self.n_joints, 3),
                shape_j.reshape(-1, self.n_shape_params),
            )
            return core.ManoOutput(
                *(x.reshape(*lead, *x.shape[1:]) for x in out)
            )
        # Single-pose jax path: through the specialization cache — the
        # dominant wrapper pattern is pose-only updates on one subject,
        # and the split is bit-identical to core.jit_forward at this
        # (unbatched) structure (pinned in tests/test_specialize.py).
        # The cache key hashes the HOST-side shape argument; a
        # device-resident betas array would force a blocking D2H
        # readback per call (the tunnel's degradation class, see
        # bench.py config1), so that rare caller keeps the one-jit path.
        if isinstance(shape, jax.Array):
            return core.jit_forward(self._params_jax, pose_j, shape_j)
        return core.jit_forward_posed(self.specialize(shape), pose_j)
