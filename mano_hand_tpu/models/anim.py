"""Temporal sequence evaluation: frames x hands in one XLA program.

The reference animates by looping ``set_params`` per frame in Python and
rendering each mesh (/root/reference/data_explore.py:12-15). Here a whole
two-hand motion clip is one vmapped forward over the (frame, hand) axes
(BASELINE.json config 5), with an optional pose resampler for retiming
clips.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from mano_hand_tpu.assets.schema import ManoParams
from mano_hand_tpu.models import core


def evaluate_sequence(
    params: ManoParams,
    poses: jnp.ndarray,                 # [T, 16, 3]
    shapes: Optional[jnp.ndarray] = None,  # [T, S] or [S] (broadcast)
) -> jnp.ndarray:
    """Verts [T, V, 3] for a single-hand motion clip (jitted, one program)."""
    poses = jnp.asarray(poses)
    t = poses.shape[0]
    dtype = params.v_template.dtype
    if shapes is None:
        shapes = jnp.zeros((t, params.shape_basis.shape[-1]), dtype)
    else:
        shapes = jnp.broadcast_to(
            jnp.asarray(shapes, dtype),
            (t, params.shape_basis.shape[-1]),
        )
    return core.jit_forward_batched(params, poses, shapes).verts


def evaluate_two_hand_sequence(
    left: ManoParams,
    right: ManoParams,
    poses: jnp.ndarray,                 # [T, 2, 16, 3] (hand axis: L, R)
    shapes: Optional[jnp.ndarray] = None,  # [T, 2, S] optional
) -> jnp.ndarray:
    """Verts [T, 2, V, 3] for a two-hand clip — vmap over (frame, hand).

    The hand axis maps to two parameter PyTrees (left/right are separate
    assets, /root/reference/dump_model.py:48-49), so each hand evaluates
    under its own params inside one compiled program.
    """
    poses = jnp.asarray(poses)
    t = poses.shape[0]
    if shapes is None:
        s_dim = left.shape_basis.shape[-1]
        shapes = jnp.zeros((t, 2, s_dim), left.v_template.dtype)

    return _run_two_hand(left, right, poses, jnp.asarray(shapes))


@jax.jit
def _run_two_hand(left, right, p, s):
    # Params are jit arguments on purpose: a device array captured as a jit
    # constant degrades every later dispatch on the axon TPU tunnel to ~70 ms.
    vl = core.forward_batched(left, p[:, 0], s[:, 0]).verts
    vr = core.forward_batched(right, p[:, 1], s[:, 1]).verts
    return jnp.stack([vl, vr], axis=1)


def resample_poses(poses: np.ndarray, n_frames: int) -> np.ndarray:
    """Linearly retime an axis-angle pose track [T, ...] to n_frames.

    Linear interpolation of axis-angle vectors is exact for fixed axes and a
    good small-angle approximation otherwise — sufficient for retiming
    scan-pose banks; use a quaternion path if long-arc accuracy matters.
    """
    poses = np.asarray(poses)
    t = poses.shape[0]
    if t == n_frames:
        return poses.copy()
    src = np.linspace(0.0, t - 1.0, n_frames)
    lo = np.floor(src).astype(int)
    hi = np.minimum(lo + 1, t - 1)
    w = (src - lo).reshape((-1,) + (1,) * (poses.ndim - 1))
    return (1.0 - w) * poses[lo] + w * poses[hi]
