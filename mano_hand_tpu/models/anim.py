"""Temporal sequence evaluation: frames x hands in one XLA program.

The reference animates by looping ``set_params`` per frame in Python and
rendering each mesh (/root/reference/data_explore.py:12-15). Here a whole
two-hand motion clip is one vmapped forward over the (frame, hand) axes
(BASELINE.json config 5), with an optional pose resampler for retiming
clips.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from mano_hand_tpu.assets.schema import ManoParams
from mano_hand_tpu.models import core


def evaluate_sequence(
    params: ManoParams,
    poses: jnp.ndarray,                 # [T, 16, 3]
    shapes: Optional[jnp.ndarray] = None,  # [T, S] or [S] (broadcast)
) -> jnp.ndarray:
    """Verts [T, V, 3] for a single-hand motion clip (jitted, one program)."""
    poses = jnp.asarray(poses)
    t = poses.shape[0]
    dtype = params.v_template.dtype
    if shapes is None:
        shapes = jnp.zeros((t, params.shape_basis.shape[-1]), dtype)
    else:
        shapes = jnp.broadcast_to(
            jnp.asarray(shapes, dtype),
            (t, params.shape_basis.shape[-1]),
        )
    return core.jit_forward_batched(params, poses, shapes).verts


def evaluate_two_hand_sequence(
    left: ManoParams,
    right: ManoParams,
    poses: jnp.ndarray,                 # [T, 2, 16, 3] (hand axis: L, R)
    shapes: Optional[jnp.ndarray] = None,  # [T, 2, S] optional
) -> jnp.ndarray:
    """Verts [T, 2, V, 3] for a two-hand clip — vmap over (frame, hand).

    The hand axis maps to two parameter PyTrees (left/right are separate
    assets, /root/reference/dump_model.py:48-49), so each hand evaluates
    under its own params inside one compiled program.
    """
    poses = jnp.asarray(poses)
    t = poses.shape[0]
    if shapes is None:
        s_dim = left.shape_basis.shape[-1]
        shapes = jnp.zeros((t, 2, s_dim), left.v_template.dtype)

    stacked = _stacked_cached(left, right)
    return _run_two_hand(stacked, poses, jnp.asarray(shapes))


# stack_params re-stacks (and re-uploads) the full left+right parameter set
# (~10 MB of leaves) — costly per frame-batch on the axon TPU tunnel. Cache
# by identity of the (left, right) pair: params PyTrees are frozen
# dataclasses reused across calls, so identity is the natural key.
_STACK_CACHE: dict = {}


def _stacked_cached(left: ManoParams, right: ManoParams) -> ManoParams:
    key = (id(left), id(right))
    hit = _STACK_CACHE.get(key)
    # Keep the originals alive in the entry so ids can't be recycled.
    if hit is not None and hit[0] is left and hit[1] is right:
        return hit[2]
    stacked = core.stack_params(left, right)
    if len(_STACK_CACHE) >= 8:   # bound: a handful of asset pairs at most
        _STACK_CACHE.clear()
    _STACK_CACHE[key] = (left, right, stacked)
    return stacked


@jax.jit
def _run_two_hand(stacked, p, s):
    # Params are jit arguments on purpose: a device array captured as a jit
    # constant degrades every later dispatch on the axon TPU tunnel to
    # ~70 ms. The hand axis vmaps over the stacked param PyTree, so both
    # hands run as one hand-batched program.
    out = core.forward_hands(
        stacked, p.transpose(1, 0, 2, 3), s.transpose(1, 0, 2)
    )
    return out.verts.transpose(1, 0, 2, 3)


def resample_poses(poses: np.ndarray, n_frames: int) -> np.ndarray:
    """Linearly retime an axis-angle pose track [T, ...] to n_frames.

    Linear interpolation of axis-angle vectors is exact for fixed axes and a
    good small-angle approximation otherwise — sufficient for retiming
    scan-pose banks; ``resample_poses_slerp`` is the long-arc-exact path.
    """
    poses = np.asarray(poses)
    t = poses.shape[0]
    if t == n_frames:
        return poses.copy()
    src = np.linspace(0.0, t - 1.0, n_frames)
    lo = np.floor(src).astype(int)
    hi = np.minimum(lo + 1, t - 1)
    w = (src - lo).reshape((-1,) + (1,) * (poses.ndim - 1))
    return (1.0 - w) * poses[lo] + w * poses[hi]


def _aa_to_quat(aa: np.ndarray) -> np.ndarray:
    """Axis-angle [..., 3] -> unit quaternion [..., 4] (w, xyz)."""
    angle = np.linalg.norm(aa, axis=-1, keepdims=True)
    half = 0.5 * angle
    # sin(x)/x via sinc (numpy sinc is sin(pi x)/(pi x)): exact limit at 0.
    k = 0.5 * np.sinc(half / np.pi)
    return np.concatenate([np.cos(half), aa * k], axis=-1)


def _quat_to_aa(q: np.ndarray) -> np.ndarray:
    """Unit quaternion [..., 4] -> axis-angle [..., 3], angle in [0, pi]."""
    q = q * np.sign(np.where(q[..., :1] == 0, 1.0, q[..., :1]))  # w >= 0
    w = np.clip(q[..., :1], -1.0, 1.0)
    vec = q[..., 1:]
    norm = np.linalg.norm(vec, axis=-1, keepdims=True)
    angle = 2.0 * np.arctan2(norm, w)
    scale = np.where(norm > 1e-12, angle / np.maximum(norm, 1e-12), 2.0)
    # Near identity, q_vec ~= aa/2, so aa ~= 2*vec: the 2.0 fallback above.
    return vec * scale


def resample_poses_slerp(poses: np.ndarray, n_frames: int) -> np.ndarray:
    """Retime an axis-angle track [T, J, 3] via per-joint quaternion slerp.

    Exact on SO(3) geodesics for any arc length — the upgrade over
    ``resample_poses`` when scan keyframes are sparse or rotations large.

    Output is in CANONICAL axis-angle form: angle in [0, pi]. Inputs with
    |aa| > pi denote the same rotation as their conjugate (2*pi - theta,
    negated axis) and come back in that canonical form, so the track is
    representation-preserving only for |aa| <= pi; rotations themselves
    (and thus forward() output) are always preserved. Post-processing that
    differentiates the raw axis-angle values (e.g. finite-difference
    velocities) should canonicalize its input first.
    """
    poses = np.asarray(poses, np.float64)
    t = poses.shape[0]
    if t == n_frames:
        # Still canonicalize (quat round-trip) so the output representation
        # is n_frames-independent.
        return _quat_to_aa(_aa_to_quat(poses))
    q = _aa_to_quat(poses)                          # [T, J, 4]
    src = np.linspace(0.0, t - 1.0, n_frames)
    lo = np.floor(src).astype(int)
    hi = np.minimum(lo + 1, t - 1)
    w = (src - lo).reshape(-1, 1, 1)
    qa, qb = q[lo], q[hi]                           # [N, J, 4]
    # Shortest path: flip hemisphere where the pair straddles it.
    dot = (qa * qb).sum(-1, keepdims=True)
    qb = np.where(dot < 0, -qb, qb)
    dot = np.clip(np.abs(dot), -1.0, 1.0)
    theta = np.arccos(dot)
    sin_theta = np.sin(theta)
    small = sin_theta < 1e-6
    wa = np.where(small, 1.0 - w, np.sin((1.0 - w) * theta) / np.where(small, 1.0, sin_theta))
    wb = np.where(small, w, np.sin(w * theta) / np.where(small, 1.0, sin_theta))
    out = wa * qa + wb * qb
    out = out / np.linalg.norm(out, axis=-1, keepdims=True)
    return _quat_to_aa(out)
