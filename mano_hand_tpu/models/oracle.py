"""Float64 NumPy oracle: the numerical ground truth for every JAX-path test.

A faithful, functional re-expression of the reference forward pass
(/root/reference/mano_np.py:79-148), preserving its quirks exactly:

  * theta is clamped to float64 eps before normalizing (mano_np.py:132);
  * the pose corrective uses (R[1:] - I).ravel() in row-major order, i.e. the
    global-rotation joint is excluded (mano_np.py:87-91);
  * "rest_verts" is the pose-and-shape-corrected mesh BEFORE skinning
    (mano_np.py:93), not the template;
  * the PCA decode is coeffs @ basis[:n] + mean, then the global-rot row is
    prepended (mano_np.py:67-72).

Unlike the reference's stateful class, this module is pure functions over a
ManoParams PyTree, mirroring the JAX core's API one-to-one so the two paths
can be diffed stage by stage.
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np

from mano_hand_tpu.assets.schema import ManoParams


class ManoOutputs(NamedTuple):
    """Everything the reference exposes after an update (mano_np.py:41-44)."""

    verts: np.ndarray        # [V, 3] posed, skinned mesh
    joints: np.ndarray       # [J, 3] rest-pose joint locations (self.J)
    rest_verts: np.ndarray   # [V, 3] blendshaped mesh before skinning
    rot_mats: np.ndarray     # [J, 3, 3] per-joint rotations (self.R)
    posed_joints: np.ndarray  # [J, 3] world joint locations after FK (extra)


def rodrigues(axis_angle: np.ndarray) -> np.ndarray:
    """Axis-angle [..., 3] -> rotation matrices [..., 3, 3] (float64).

    Same formula as the reference (mano_np.py:130-147): R = cos(t) I +
    (1 - cos(t)) rr^T + sin(t) K(r_hat), with t clamped to f64 eps.
    """
    aa = np.asarray(axis_angle, dtype=np.float64)
    theta = np.sqrt((aa * aa).sum(axis=-1, keepdims=True))
    theta = np.maximum(theta, np.finfo(np.float64).eps)
    axis = aa / theta
    x, y, z = axis[..., 0], axis[..., 1], axis[..., 2]
    zero = np.zeros_like(x)
    K = np.stack(
        [zero, -z, y, z, zero, -x, -y, x, zero], axis=-1
    ).reshape(*axis.shape[:-1], 3, 3)
    outer = axis[..., :, None] * axis[..., None, :]
    c = np.cos(theta)[..., None]
    s = np.sin(theta)[..., None]
    eye = np.broadcast_to(np.eye(3), outer.shape)
    return c * eye + (1.0 - c) * outer + s * K


def decode_pca_pose(
    params: ManoParams,
    pca_coeffs: np.ndarray,
    global_rot: np.ndarray | None = None,
) -> np.ndarray:
    """PCA coefficients [n<=(J-1)*3] (+ optional global rot [3]) -> pose
    [J, 3].

    Semantics of mano_np.py:66-72: truncated basis rows, add mean, reshape
    to [J-1, 3] (15 for MANO), prepend the global-rotation row (zeros if
    not given).
    """
    pca_coeffs = np.asarray(pca_coeffs, dtype=np.float64)
    n = pca_coeffs.shape[-1]
    flat = pca_coeffs @ np.asarray(params.pca_basis)[:n] + np.asarray(params.pca_mean)
    fingers = flat.reshape(np.asarray(params.pca_mean).shape[-1] // 3, 3)
    root = (
        np.zeros((1, 3))
        if global_rot is None
        else np.asarray(global_rot, dtype=np.float64).reshape(1, 3)
    )
    return np.concatenate([root, fingers], axis=0)


def forward(
    params: ManoParams,
    pose: np.ndarray | None = None,
    shape: np.ndarray | None = None,
) -> ManoOutputs:
    """Full MANO forward pass: blendshapes -> joints -> FK -> LBS.

    pose: [16, 3] axis-angle per joint (row 0 = global rotation).
    shape: [10] shape coefficients.
    """
    n_joints = params.j_regressor.shape[0]
    pose = (
        np.zeros((n_joints, 3)) if pose is None
        else np.asarray(pose, dtype=np.float64).reshape(n_joints, 3)
    )
    shape = (
        np.zeros(params.shape_basis.shape[-1]) if shape is None
        else np.asarray(shape, dtype=np.float64)
    )
    template = np.asarray(params.v_template, dtype=np.float64)
    shape_basis = np.asarray(params.shape_basis, dtype=np.float64)
    pose_basis = np.asarray(params.pose_basis, dtype=np.float64)
    j_reg = np.asarray(params.j_regressor, dtype=np.float64)
    weights = np.asarray(params.lbs_weights, dtype=np.float64)

    # 1. Shape blendshape (mano_np.py:81) and joint regression (mano_np.py:83).
    v_shaped = template + shape_basis @ shape
    joints = j_reg @ v_shaped

    # 2. Per-joint rotations and pose corrective (mano_np.py:84-91). The
    #    corrective is driven by (R - I) of the 15 articulated joints only.
    rot_mats = rodrigues(pose)
    pose_feat = (rot_mats[1:] - np.eye(3)).ravel()
    v_posed = v_shaped + pose_basis @ pose_feat
    rest_verts = v_posed  # reference naming (mano_np.py:93)

    # 3. Forward kinematics along the parent chain (mano_np.py:96-104),
    #    expressed as (rotation, translation) pairs instead of 4x4 stacking.
    world_rot = np.empty((n_joints, 3, 3))
    world_t = np.empty((n_joints, 3))
    world_rot[0] = rot_mats[0]
    world_t[0] = joints[0]
    for i in range(1, n_joints):
        p = params.parents[i]
        local_t = joints[i] - joints[p]
        world_rot[i] = world_rot[p] @ rot_mats[i]
        world_t[i] = world_rot[p] @ local_t + world_t[p]
    posed_joints = world_t.copy()

    # 4. Inverse-bind (mano_np.py:106-110): subtract each joint's rest
    #    position as carried through its world transform, so skinning maps
    #    rest-pose verts directly to posed verts.
    skin_t = world_t - np.einsum("jab,jb->ja", world_rot, joints)

    # 5. LBS (mano_np.py:112-115), fused: blend rotations and translations
    #    per vertex, never materializing [V, 4, 4].
    blend_rot = np.einsum("vj,jab->vab", weights, world_rot)
    blend_t = weights @ skin_t
    verts = np.einsum("vab,vb->va", blend_rot, v_posed) + blend_t

    return ManoOutputs(
        verts=verts,
        joints=joints,
        rest_verts=rest_verts,
        rot_mats=rot_mats,
        posed_joints=posed_joints,
    )
