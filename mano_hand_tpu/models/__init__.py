from mano_hand_tpu.models.core import (
    ManoOutput,
    decode_pca,
    forward,
    forward_batched,
    forward_chunked,
    forward_fused,
    forward_pca,
    fused_blend_bases,
    jit_forward,
    jit_forward_batched,
)
from mano_hand_tpu.models import oracle

__all__ = [
    "ManoOutput",
    "decode_pca",
    "forward",
    "forward_batched",
    "forward_chunked",
    "forward_fused",
    "forward_pca",
    "fused_blend_bases",
    "jit_forward",
    "jit_forward_batched",
    "oracle",
]
