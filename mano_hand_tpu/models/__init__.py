from mano_hand_tpu.models.core import (
    ManoOutput,
    decode_pca,
    forward,
    forward_batched,
    forward_chunked,
    forward_fused,
    forward_hands,
    forward_pca,
    fused_blend_bases,
    jit_forward,
    jit_forward_batched,
    keypoints,
    stack_params,
)
from mano_hand_tpu.models import oracle

__all__ = [
    "forward_hands",
    "stack_params",
    "ManoOutput",
    "decode_pca",
    "forward",
    "forward_batched",
    "forward_chunked",
    "forward_fused",
    "forward_pca",
    "fused_blend_bases",
    "jit_forward",
    "jit_forward_batched",
    "keypoints",
    "oracle",
]
