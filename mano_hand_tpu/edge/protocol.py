"""The edge wire protocol: encodings, headers, and status mapping.

One module owns every byte-level convention the server and client share
(the METRICS_JSON filename-contract rule: a rename applied to one side
cannot silently break the other):

* **Arrays** travel as ``{"b64": ..., "shape": [...], "dtype": ...}`` —
  base64 of the raw little-endian C-contiguous bytes. LOSSLESS by
  construction: a float32 array decodes to the identical bits on the
  far side, which is what lets the config18 drill judge wire results
  BIT-identical to in-process ``submit``/``submit_frame`` (the PR-4
  contract extended across the network boundary). JSON-float round
  trips (repr/parse) are banned from every numeric payload.
* **Request metadata** rides headers: ``X-Mano-Priority`` is the PR-5
  admission tier, ``X-Mano-Deadline-S`` the end-to-end TTL — so a
  proxy/load-balancer can read (and rewrite) QoS without touching the
  body.
* **Terminal kinds -> HTTP status**: the engine's structured
  ``ServingError`` kinds map 1:1 onto status codes (below), so a
  client can branch on status alone and the JSON error body carries
  the full structured kind/phase/message for logging.
* **Backpressure**: a shed maps to 429 with a per-tier ``Retry-After``
  derived from the PR-5 ``load()`` snapshot — tier 0 retries soonest
  (its quota headroom is reserved by construction), lower-priority
  tiers are told to wait longer, and a tier already hard-shedding gets
  an extra second on top of a merely "busy" one.
* **Streams** upgrade the connection (``Upgrade: mano-stream/1`` ->
  ``101``) and then speak newline-delimited JSON both ways: requests
  ``{"op": "open"|"frame"|"close", ...}``, responses
  ``{"event": ...}`` or ``{"error": {...}}`` — one line per frame,
  ordered, over one persistent socket (the PR-12 session is
  connection-affine: the socket dying IS the client disappearing).
"""

from __future__ import annotations

import base64
import json
from typing import Optional

import numpy as np

EDGE_SCHEMA = 1

#: Upgrade token for the PR-12 stream protocol (open/frame/close over
#: one persistent connection).
STREAM_UPGRADE = "mano-stream/1"

#: Request-metadata headers (lower-case — header lookup is
#: case-insensitive and the parser normalizes).
PRIORITY_HEADER = "x-mano-priority"
DEADLINE_HEADER = "x-mano-deadline-s"

#: ServingError.kind -> HTTP status. "cancelled" is absent by design:
#: a cancelled request's client is GONE (cancellation is what the
#: server does on its disconnect), so there is nobody to answer.
KIND_STATUS = {
    "shed": 429,        # admission refused — back off and retry
    "expired": 504,     # the request's own deadline elapsed unserved
    "shutdown": 503,    # the engine is stopping/stopped
    "error": 500,       # dispatch failure — flight record attached
    "upstream": 502,    # the PROXY (PR 18) lost a backend mid-response
    #   after the request hit its wire — re-sending is not safe (the
    #   worker may have admitted the work), so the client decides.
    #   A backend down AT CONNECT never surfaces this: the proxy
    #   re-routes to a sibling (nothing was dispatched — idempotent).
}

_REASONS = {
    200: "OK", 400: "Bad Request", 404: "Not Found",
    405: "Method Not Allowed", 408: "Request Timeout",
    413: "Payload Too Large", 429: "Too Many Requests",
    500: "Internal Server Error", 502: "Bad Gateway",
    503: "Service Unavailable",
    504: "Gateway Timeout", 101: "Switching Protocols",
}


def reason(status: int) -> str:
    return _REASONS.get(status, "Unknown")


# ------------------------------------------------------------------ arrays
def encode_array(arr) -> dict:
    """Lossless wire form of one ndarray (little-endian raw bytes)."""
    a = np.ascontiguousarray(arr)
    if a.dtype.byteorder == ">":        # exotic caller: normalize
        a = a.astype(a.dtype.newbyteorder("<"))
    return {
        "b64": base64.b64encode(a.tobytes()).decode("ascii"),
        "shape": list(a.shape),
        "dtype": a.dtype.name,
    }


def decode_array(obj: dict) -> np.ndarray:
    """Inverse of ``encode_array``; raises ValueError on a malformed
    payload (the server maps that to 400, never a 500)."""
    if not isinstance(obj, dict) or "b64" not in obj:
        raise ValueError("array payload must be {b64, shape, dtype}")
    try:
        raw = base64.b64decode(obj["b64"], validate=True)
        dtype = np.dtype(obj.get("dtype", "float32")).newbyteorder("<")
        shape = tuple(int(s) for s in obj.get("shape", []))
    except Exception as e:  # noqa: BLE001 — classify as caller error
        raise ValueError(f"malformed array payload: {e}") from e
    n = int(np.prod(shape, dtype=np.int64)) if shape else 1
    if dtype.itemsize * n != len(raw):
        raise ValueError(
            f"array payload size mismatch: {len(raw)} bytes for "
            f"shape {shape} dtype {dtype.name}")
    return np.frombuffer(raw, dtype=dtype).reshape(shape).copy()


# ------------------------------------------------------------ backpressure
def retry_after_s(tier: int, load: Optional[dict] = None) -> int:
    """Per-tier Retry-After (whole seconds, the header's delay form).

    Tier 0 is told to retry soonest — the PR-5 quota ladder reserves
    its headroom, so a tier-0 shed clears as fast as one coalesce
    window drains. Lower tiers wait longer (they are the ones overload
    sheds FIRST and should be the last back in the door). A tier whose
    ``load()`` admission state is already "shed" gets one extra second
    over a merely "busy" one — the signal an adaptive client needs to
    back off harder while the burn is live.
    """
    base = 1 if tier <= 0 else min(1 + int(tier), 4)
    state = ((load or {}).get("admission") or {}).get(str(int(tier)))
    return base + (1 if state == "shed" else 0)


# ----------------------------------------------------------------- errors
def error_body(kind: str, message: str, *, phase: str = "edge",
               flight: Optional[dict] = None) -> dict:
    """The structured JSON error payload (mirrors ServingError's
    kind/phase vocabulary; ``flight`` attaches the PR-8 capture on
    5xx incidents)."""
    body = {"error": {"kind": kind, "phase": phase, "message": message}}
    if flight is not None:
        body["flight"] = flight
    return body


def dumps(obj) -> bytes:
    """Compact one-line JSON bytes (NDJSON-safe: no embedded newlines)."""
    return json.dumps(obj, separators=(",", ":")).encode("utf-8")
