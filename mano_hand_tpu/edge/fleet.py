"""Worker-process supervision for the fleet front tier (PR 18).

edge/proxy.py routes over backends it is HANDED; this module is the
half that makes those backends: spawn N ``mano serve`` worker
processes, parse each one's stdout ready line for its ephemeral port,
and keep every wait BOUNDED with a SIGKILL backstop — the r3-incident
rule (CLAUDE.md): anything long-running needs a kill -9-capable
supervisor, never a signal handler it hopes gets delivered. SIGTERM is
the polite path (the worker's documented drain), but a worker wedged
in a C-level call cannot run a Python handler, so ``terminate()``
always escalates to SIGKILL at its deadline.

The stdout contract is cmd_serve's: exactly two JSON lines — a ready
line ``{"edge": {host, port, pid, ...}}`` at bind time and an exit
line ``{"edge_exit": {...}}`` after the drain (PR 18 extends the exit
line with the worker's span accounting + compile counters, the
cross-process halves of the fleet drill's span-once and zero-recompile
judgments). A reader thread drains the pipe continuously — a worker
must never block on a full stdout pipe — and stderr goes to a per-
worker log file (or devnull) for the same reason.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

from mano_hand_tpu.edge.proxy import Backend, EdgeProxy


class WorkerSpec:
    """The knobs one ``mano serve`` worker boots with. ``extra`` is
    passed through verbatim (flags this module need not know)."""

    def __init__(self, *, asset: str = "synthetic",
                 side: Optional[str] = None,
                 platform: str = "", lanes: int = 0,
                 max_bucket: int = 64, max_delay_ms: float = 2.0,
                 max_queued: int = 256, max_subjects: int = 0,
                 aot_dir: str = "",
                 store_warm_capacity: int = 0,
                 no_warmup: bool = False,
                 warm_streams: bool = False,
                 drain_timeout_s: float = 15.0,
                 device_lock: str = "auto",
                 extra: Sequence[str] = (),
                 extra_env: Optional[Dict[str, str]] = None):
        self.asset = asset
        self.side = side
        self.platform = platform
        self.lanes = int(lanes)
        self.max_bucket = int(max_bucket)
        self.max_delay_ms = float(max_delay_ms)
        self.max_queued = int(max_queued)
        self.max_subjects = int(max_subjects)
        self.aot_dir = aot_dir
        self.store_warm_capacity = int(store_warm_capacity)
        self.no_warmup = bool(no_warmup)
        self.warm_streams = bool(warm_streams)
        self.drain_timeout_s = float(drain_timeout_s)
        self.device_lock = device_lock
        self.extra = tuple(extra)
        self.extra_env = dict(extra_env or {})

    def argv(self) -> List[str]:
        cmd = [sys.executable, "-m", "mano_hand_tpu.cli"]
        if self.platform:
            cmd += ["--platform", self.platform]
        cmd += ["serve", "--host", "127.0.0.1", "--port", "0",
                "--asset", self.asset,
                "--max-bucket", str(self.max_bucket),
                "--max-delay-ms", repr(self.max_delay_ms),
                "--max-queued", str(self.max_queued),
                "--drain-timeout-s", repr(self.drain_timeout_s),
                "--device-lock", self.device_lock]
        if self.side:
            cmd += ["--side", self.side]
        if self.lanes:
            cmd += ["--lanes", str(self.lanes)]
        if self.max_subjects:
            cmd += ["--max-subjects", str(self.max_subjects)]
        if self.aot_dir:
            cmd += ["--aot-dir", self.aot_dir]
        if self.store_warm_capacity:
            cmd += ["--store-warm-capacity",
                    str(self.store_warm_capacity)]
        if self.no_warmup:
            cmd += ["--no-warmup"]
        if self.warm_streams:
            cmd += ["--warm-streams"]
        cmd += list(self.extra)
        return cmd


class WorkerProc:
    """One supervised ``mano serve`` process.

    ``start()`` spawns it; ``wait_ready()`` blocks (bounded, SIGKILL
    on timeout) until the stdout ready line names the bound port;
    ``terminate()`` is SIGTERM + bounded wait + SIGKILL backstop;
    ``kill()`` is the chaos drill's instant SIGKILL. ``exit_report``
    holds the parsed ``edge_exit`` line once the process printed one
    (a SIGKILLed worker never does — by construction)."""

    def __init__(self, name: str, spec: WorkerSpec, *,
                 env: Optional[Dict[str, str]] = None,
                 stderr_path: Optional[str] = None,
                 log: Optional[Callable[[str], None]] = None):
        self.name = name
        self.spec = spec
        self._env = env
        self._stderr_path = stderr_path
        self._log = log or (lambda m: None)
        self._proc: Optional[subprocess.Popen] = None
        self._reader: Optional[threading.Thread] = None
        self._stderr_f = None
        self._ready = threading.Event()
        self._exited = threading.Event()
        self.ready_info: Optional[dict] = None
        self.exit_report: Optional[dict] = None
        self.stdout_lines: List[str] = []
        self.returncode: Optional[int] = None

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "WorkerProc":
        if self._proc is not None:
            return self
        env = dict(os.environ)
        if self._env:
            env.update(self._env)
        # Per-spec env wins over the fleet-wide env: the drill uses it
        # to give each worker its OWN compile-cache dir — N processes
        # sharing one jax_compilation_cache_dir is the XLA executable-
        # deserialization crash class (CLAUDE.md), and workers inherit
        # MANO_TEST_CACHE_DIR from a pytest parent unless overridden.
        if self.spec.extra_env:
            env.update(self.spec.extra_env)
        if self._stderr_path:
            self._stderr_f = open(self._stderr_path, "ab")
            stderr = self._stderr_f
        else:
            stderr = subprocess.DEVNULL
        self._proc = subprocess.Popen(
            self.spec.argv(), stdout=subprocess.PIPE, stderr=stderr,
            env=env, start_new_session=True)
        self._reader = threading.Thread(
            target=self._drain_stdout, name=f"stdout-{self.name}",
            daemon=True)
        self._reader.start()
        return self

    def _drain_stdout(self) -> None:
        proc = self._proc
        try:
            for raw in proc.stdout:
                line = raw.decode("utf-8", "replace").rstrip("\n")
                self.stdout_lines.append(line)
                try:
                    d = json.loads(line)
                except ValueError:
                    continue
                if "edge" in d:
                    self.ready_info = d["edge"]
                    self._ready.set()
                elif "edge_exit" in d:
                    self.exit_report = d["edge_exit"]
        except (OSError, ValueError):
            pass
        finally:
            self._exited.set()
            self._ready.set()           # never strand a ready waiter

    @property
    def pid(self) -> Optional[int]:
        return None if self._proc is None else self._proc.pid

    @property
    def port(self) -> Optional[int]:
        return (None if self.ready_info is None
                else int(self.ready_info["port"]))

    def alive(self) -> bool:
        return self._proc is not None and self._proc.poll() is None

    def wait_ready(self, timeout_s: float = 120.0) -> "WorkerProc":
        """Block until the ready line lands; a worker that failed (or
        wedged) before binding is SIGKILLed and reported — a boot must
        never hang the fleet."""
        if not self._ready.wait(timeout=timeout_s):
            self.kill()
            raise RuntimeError(
                f"worker {self.name} did not become ready within "
                f"{timeout_s}s")
        if self.ready_info is None:
            rc = self._proc.poll() if self._proc else None
            self.kill()
            raise RuntimeError(
                f"worker {self.name} exited (rc={rc}) before its "
                f"ready line; stdout: {self.stdout_lines[-3:]}")
        return self

    def kill(self) -> None:
        """The chaos path: SIGKILL now. The process gets no drain, no
        exit line, and its tracer dies with it (the drill's span
        accounting excludes it by construction)."""
        if self._proc is None:
            return
        try:
            self._proc.kill()
        except OSError:
            pass
        self._finish(join_timeout_s=10.0)

    def terminate(self, timeout_s: float = 30.0) -> Optional[dict]:
        """The polite path: SIGTERM (the worker drains and prints its
        exit line), bounded by ``timeout_s`` with a SIGKILL backstop —
        SIGTERM needs the worker's main thread, and a worker wedged in
        a C-level call never runs the handler (CLAUDE.md). Returns the
        parsed exit report (None if the backstop fired first)."""
        if self._proc is None:
            return None
        if self._proc.poll() is None:
            try:
                self._proc.send_signal(signal.SIGTERM)
            except OSError:
                pass
        deadline = time.monotonic() + timeout_s
        try:
            self._proc.wait(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            self._log(f"worker {self.name}: SIGTERM deadline hit — "
                      f"SIGKILL backstop")
            try:
                self._proc.kill()
            except OSError:
                pass
        self._finish(join_timeout_s=max(1.0,
                                        deadline - time.monotonic()))
        return self.exit_report

    def _finish(self, join_timeout_s: float) -> None:
        try:
            self._proc.wait(timeout=join_timeout_s)
        except subprocess.TimeoutExpired:
            pass
        self.returncode = self._proc.poll()
        if self._reader is not None:
            self._reader.join(timeout=join_timeout_s)
        if self._stderr_f is not None:
            try:
                self._stderr_f.close()
            except OSError:
                pass
            self._stderr_f = None


class Fleet:
    """N workers + one proxy, as a unit: the rolling-deploy substrate.

    ``start()`` boots every worker (bounded), waits for all ready
    lines, then fronts them with an ``EdgeProxy``. ``kill_worker``
    (chaos) and ``drain_worker`` (deploy: proxy-side stream migration,
    then SIGTERM) are the two removal paths the config21 drill
    exercises; ``stop()`` tears the whole thing down and returns every
    worker's exit report."""

    def __init__(self, specs: Sequence[WorkerSpec], *,
                 env: Optional[Dict[str, str]] = None,
                 stderr_dir: Optional[str] = None,
                 proxy_kwargs: Optional[dict] = None,
                 log: Optional[Callable[[str], None]] = None):
        self._log = log or (lambda m: None)
        self._env = env
        self._stderr_dir = stderr_dir
        self.workers: Dict[str, WorkerProc] = {}
        for i, spec in enumerate(specs):
            name = f"w{i}"
            stderr_path = (os.path.join(stderr_dir, f"{name}.stderr")
                           if stderr_dir else None)
            self.workers[name] = WorkerProc(
                name, spec, env=env, stderr_path=stderr_path,
                log=self._log)
        self._proxy_kwargs = dict(proxy_kwargs or {})
        self.proxy: Optional[EdgeProxy] = None
        self.exit_reports: Dict[str, Optional[dict]] = {}

    def start(self, ready_timeout_s: float = 180.0) -> "Fleet":
        t0 = time.monotonic()
        for w in self.workers.values():
            w.start()
        for w in self.workers.values():
            left = max(1.0, ready_timeout_s - (time.monotonic() - t0))
            try:
                w.wait_ready(timeout_s=left)
            except RuntimeError:
                self.stop(timeout_s=10.0)
                raise
        backends = [Backend(name, "127.0.0.1", w.port)
                    for name, w in self.workers.items()]
        self.proxy = EdgeProxy(backends, log=self._log,
                               **self._proxy_kwargs).start()
        return self

    def add_worker(self, spec: WorkerSpec, *,
                   ready_timeout_s: float = 180.0,
                   stderr_dir: Optional[str] = None,
                   env: Optional[Dict[str, str]] = None) -> str:
        """Scale-up: boot one NEW worker and route to it only once it
        is genuinely warm (the PR-18 "cold stream starts on scale-up
        workers" remainder).

        The ordering is the contract: the worker boots, runs its full
        warmup — including, with ``spec.warm_streams``, the in-process
        stream-fit warm pass (cmd_serve's ``--warm-streams``: the
        fit-stage programs are NOT in the AOT lattice, per the PR-18
        dead-end, so the worker exercises one synthetic stream before
        printing its ready line) — and ONLY THEN is handed to
        ``proxy.add_backend``, which replays every known specialize
        before traffic can land. A fresh worker's first real frame
        pays zero compiles; test_fleet.py pins it via /metrics.

        Returns the new worker's name (``w<N>``, continuing the boot
        numbering)."""
        if self.proxy is None:
            raise RuntimeError("fleet is not started")
        env = self._env if env is None else env
        stderr_dir = (self._stderr_dir if stderr_dir is None
                      else stderr_dir)
        i = len(self.workers)
        while f"w{i}" in self.workers:
            i += 1
        name = f"w{i}"
        stderr_path = (os.path.join(stderr_dir, f"{name}.stderr")
                       if stderr_dir else None)
        w = WorkerProc(name, spec, env=env, stderr_path=stderr_path,
                       log=self._log)
        self.workers[name] = w
        try:
            w.start().wait_ready(timeout_s=ready_timeout_s)
        except RuntimeError:
            del self.workers[name]
            raise
        self.proxy.add_backend(Backend(name, "127.0.0.1", w.port))
        self._log(f"fleet: added worker {name} on port {w.port}")
        return name

    def kill_worker(self, name: str) -> None:
        """Chaos: SIGKILL one worker. The proxy discovers the death
        through its breaker / mid-frame failover — nothing is told in
        advance, which is the point of the drill."""
        self.workers[name].kill()
        self.exit_reports[name] = None

    def drain_worker(self, name: str, *,
                     migrate_timeout_s: float = 10.0,
                     term_timeout_s: float = 30.0) -> dict:
        """Rolling deploy: migrate the worker's proxied streams to
        siblings (bounded), then SIGTERM it so its own drain closes
        any remaining local state and prints the exit line."""
        if self.proxy is None:
            raise RuntimeError("fleet is not started")
        report = self.proxy.drain_backend(
            name, timeout_s=migrate_timeout_s)
        self.exit_reports[name] = self.workers[name].terminate(
            timeout_s=term_timeout_s)
        return report

    def stop(self, timeout_s: float = 30.0) -> Dict[str, Optional[dict]]:
        if self.proxy is not None:
            try:
                self.proxy.drain(timeout_s=min(10.0, timeout_s))
            except Exception:  # noqa: BLE001 — teardown must finish
                pass
        for name, w in self.workers.items():
            if name not in self.exit_reports or (
                    self.exit_reports[name] is None and w.alive()):
                self.exit_reports[name] = w.terminate(
                    timeout_s=timeout_s)
        return dict(self.exit_reports)
