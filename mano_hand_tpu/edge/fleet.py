"""Worker-process supervision for the fleet front tier (PR 18).

edge/proxy.py routes over backends it is HANDED; this module is the
half that makes those backends: spawn N ``mano serve`` worker
processes, parse each one's stdout ready line for its ephemeral port,
and keep every wait BOUNDED with a SIGKILL backstop — the r3-incident
rule (CLAUDE.md): anything long-running needs a kill -9-capable
supervisor, never a signal handler it hopes gets delivered. SIGTERM is
the polite path (the worker's documented drain), but a worker wedged
in a C-level call cannot run a Python handler, so ``terminate()``
always escalates to SIGKILL at its deadline.

The stdout contract is cmd_serve's: exactly two JSON lines — a ready
line ``{"edge": {host, port, pid, ...}}`` at bind time and an exit
line ``{"edge_exit": {...}}`` after the drain (PR 18 extends the exit
line with the worker's span accounting + compile counters, the
cross-process halves of the fleet drill's span-once and zero-recompile
judgments). A reader thread drains the pipe continuously — a worker
must never block on a full stdout pipe — and stderr goes to a per-
worker log file (or devnull) for the same reason.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

from mano_hand_tpu.edge.proxy import Backend, EdgeProxy


class WorkerSpec:
    """The knobs one ``mano serve`` worker boots with. ``extra`` is
    passed through verbatim (flags this module need not know)."""

    def __init__(self, *, asset: str = "synthetic",
                 side: Optional[str] = None,
                 platform: str = "", lanes: int = 0,
                 max_bucket: int = 64, max_delay_ms: float = 2.0,
                 max_queued: int = 256, max_subjects: int = 0,
                 aot_dir: str = "",
                 store_warm_capacity: int = 0,
                 no_warmup: bool = False,
                 warm_streams: bool = False,
                 drain_timeout_s: float = 15.0,
                 device_lock: str = "auto",
                 port: int = 0,
                 extra: Sequence[str] = (),
                 extra_env: Optional[Dict[str, str]] = None):
        self.port = int(port)
        self.asset = asset
        self.side = side
        self.platform = platform
        self.lanes = int(lanes)
        self.max_bucket = int(max_bucket)
        self.max_delay_ms = float(max_delay_ms)
        self.max_queued = int(max_queued)
        self.max_subjects = int(max_subjects)
        self.aot_dir = aot_dir
        self.store_warm_capacity = int(store_warm_capacity)
        self.no_warmup = bool(no_warmup)
        self.warm_streams = bool(warm_streams)
        self.drain_timeout_s = float(drain_timeout_s)
        self.device_lock = device_lock
        self.extra = tuple(extra)
        self.extra_env = dict(extra_env or {})

    def argv(self) -> List[str]:
        cmd = [sys.executable, "-m", "mano_hand_tpu.cli"]
        if self.platform:
            cmd += ["--platform", self.platform]
        # port=0 lets the OS pick (the historical default); a FIXED
        # port is the PR-20 heal contract — a replacement worker binds
        # the DEAD worker's port, so a subprocess proxy's static
        # backend list (and any client that memorized the address)
        # stays valid with no re-wiring call.
        cmd += ["serve", "--host", "127.0.0.1", "--port", str(self.port),
                "--asset", self.asset,
                "--max-bucket", str(self.max_bucket),
                "--max-delay-ms", repr(self.max_delay_ms),
                "--max-queued", str(self.max_queued),
                "--drain-timeout-s", repr(self.drain_timeout_s),
                "--device-lock", self.device_lock]
        if self.side:
            cmd += ["--side", self.side]
        if self.lanes:
            cmd += ["--lanes", str(self.lanes)]
        if self.max_subjects:
            cmd += ["--max-subjects", str(self.max_subjects)]
        if self.aot_dir:
            cmd += ["--aot-dir", self.aot_dir]
        if self.store_warm_capacity:
            cmd += ["--store-warm-capacity",
                    str(self.store_warm_capacity)]
        if self.no_warmup:
            cmd += ["--no-warmup"]
        if self.warm_streams:
            cmd += ["--warm-streams"]
        cmd += list(self.extra)
        return cmd

    def with_port(self, port: int) -> "WorkerSpec":
        """A copy of this spec pinned to ``port`` — the supervisor's
        replacement-boot spec (same knobs, the dead worker's port)."""
        import copy

        spec = copy.copy(self)
        spec.extra = tuple(self.extra)
        spec.extra_env = dict(self.extra_env)
        spec.port = int(port)
        return spec


class WorkerProc:
    """One supervised ``mano serve`` process.

    ``start()`` spawns it; ``wait_ready()`` blocks (bounded, SIGKILL
    on timeout) until the stdout ready line names the bound port;
    ``terminate()`` is SIGTERM + bounded wait + SIGKILL backstop;
    ``kill()`` is the chaos drill's instant SIGKILL. ``exit_report``
    holds the parsed ``edge_exit`` line once the process printed one
    (a SIGKILLed worker never does — by construction)."""

    def __init__(self, name: str, spec: WorkerSpec, *,
                 env: Optional[Dict[str, str]] = None,
                 stderr_path: Optional[str] = None,
                 log: Optional[Callable[[str], None]] = None):
        self.name = name
        self.spec = spec
        self._env = env
        self._stderr_path = stderr_path
        self._log = log or (lambda m: None)
        self._proc: Optional[subprocess.Popen] = None
        self._reader: Optional[threading.Thread] = None
        self._stderr_f = None
        self._ready = threading.Event()
        self._exited = threading.Event()
        self.ready_info: Optional[dict] = None
        self.exit_report: Optional[dict] = None
        self.stdout_lines: List[str] = []
        self.returncode: Optional[int] = None

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "WorkerProc":
        if self._proc is not None:
            return self
        env = dict(os.environ)
        if self._env:
            env.update(self._env)
        # Per-spec env wins over the fleet-wide env: the drill uses it
        # to give each worker its OWN compile-cache dir — N processes
        # sharing one jax_compilation_cache_dir is the XLA executable-
        # deserialization crash class (CLAUDE.md), and workers inherit
        # MANO_TEST_CACHE_DIR from a pytest parent unless overridden.
        if self.spec.extra_env:
            env.update(self.spec.extra_env)
        if self._stderr_path:
            self._stderr_f = open(self._stderr_path, "ab")
            stderr = self._stderr_f
        else:
            stderr = subprocess.DEVNULL
        self._proc = subprocess.Popen(
            self.spec.argv(), stdout=subprocess.PIPE, stderr=stderr,
            env=env, start_new_session=True)
        self._reader = threading.Thread(
            target=self._drain_stdout, name=f"stdout-{self.name}",
            daemon=True)
        self._reader.start()
        return self

    def _drain_stdout(self) -> None:
        proc = self._proc
        try:
            for raw in proc.stdout:
                line = raw.decode("utf-8", "replace").rstrip("\n")
                self.stdout_lines.append(line)
                try:
                    d = json.loads(line)
                except ValueError:
                    continue
                if "edge" in d:
                    self.ready_info = d["edge"]
                    self._ready.set()
                elif "edge_exit" in d:
                    self.exit_report = d["edge_exit"]
        except (OSError, ValueError):
            pass
        finally:
            self._exited.set()
            self._ready.set()           # never strand a ready waiter

    @property
    def pid(self) -> Optional[int]:
        return None if self._proc is None else self._proc.pid

    @property
    def port(self) -> Optional[int]:
        return (None if self.ready_info is None
                else int(self.ready_info["port"]))

    def alive(self) -> bool:
        return self._proc is not None and self._proc.poll() is None

    def wait_ready(self, timeout_s: float = 120.0) -> "WorkerProc":
        """Block until the ready line lands; a worker that failed (or
        wedged) before binding is SIGKILLed and reported — a boot must
        never hang the fleet."""
        if not self._ready.wait(timeout=timeout_s):
            self.kill()
            raise RuntimeError(
                f"worker {self.name} did not become ready within "
                f"{timeout_s}s")
        if self.ready_info is None:
            rc = self._proc.poll() if self._proc else None
            self.kill()
            raise RuntimeError(
                f"worker {self.name} exited (rc={rc}) before its "
                f"ready line; stdout: {self.stdout_lines[-3:]}")
        return self

    def kill(self) -> None:
        """The chaos path: SIGKILL now. The process gets no drain, no
        exit line, and its tracer dies with it (the drill's span
        accounting excludes it by construction)."""
        if self._proc is None:
            return
        try:
            self._proc.kill()
        except OSError:
            pass
        self._finish(join_timeout_s=10.0)

    def terminate(self, timeout_s: float = 30.0) -> Optional[dict]:
        """The polite path: SIGTERM (the worker drains and prints its
        exit line), bounded by ``timeout_s`` with a SIGKILL backstop —
        SIGTERM needs the worker's main thread, and a worker wedged in
        a C-level call never runs the handler (CLAUDE.md). Returns the
        parsed exit report (None if the backstop fired first)."""
        if self._proc is None:
            return None
        if self._proc.poll() is None:
            try:
                self._proc.send_signal(signal.SIGTERM)
            except OSError:
                pass
        deadline = time.monotonic() + timeout_s
        try:
            self._proc.wait(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            self._log(f"worker {self.name}: SIGTERM deadline hit — "
                      f"SIGKILL backstop")
            try:
                self._proc.kill()
            except OSError:
                pass
        self._finish(join_timeout_s=max(1.0,
                                        deadline - time.monotonic()))
        return self.exit_report

    def _finish(self, join_timeout_s: float) -> None:
        try:
            self._proc.wait(timeout=join_timeout_s)
        except subprocess.TimeoutExpired:
            pass
        self.returncode = self._proc.poll()
        if self._reader is not None:
            self._reader.join(timeout=join_timeout_s)
        if self._stderr_f is not None:
            try:
                self._stderr_f.close()
            except OSError:
                pass
            self._stderr_f = None


class Fleet:
    """N workers + one proxy, as a unit: the rolling-deploy substrate.

    ``start()`` boots every worker (bounded), waits for all ready
    lines, then fronts them with an ``EdgeProxy``. ``kill_worker``
    (chaos) and ``drain_worker`` (deploy: proxy-side stream migration,
    then SIGTERM) are the two removal paths the config21 drill
    exercises; ``stop()`` tears the whole thing down and returns every
    worker's exit report."""

    def __init__(self, specs: Sequence[WorkerSpec], *,
                 env: Optional[Dict[str, str]] = None,
                 stderr_dir: Optional[str] = None,
                 proxy_kwargs: Optional[dict] = None,
                 external_proxy: bool = False,
                 log: Optional[Callable[[str], None]] = None):
        self._log = log or (lambda m: None)
        self._env = env
        self._stderr_dir = stderr_dir
        # PR 20: an externally-supervised proxy pair (ProxyPair) fronts
        # the workers instead of an in-process EdgeProxy; start() then
        # leaves self.proxy None, and the FleetSupervisor's heal path
        # re-enters routing by re-binding the dead worker's fixed port.
        self._external_proxy = bool(external_proxy)
        self.workers: Dict[str, WorkerProc] = {}
        for i, spec in enumerate(specs):
            name = f"w{i}"
            stderr_path = (os.path.join(stderr_dir, f"{name}.stderr")
                           if stderr_dir else None)
            self.workers[name] = WorkerProc(
                name, spec, env=env, stderr_path=stderr_path,
                log=self._log)
        self._proxy_kwargs = dict(proxy_kwargs or {})
        self.proxy: Optional[EdgeProxy] = None
        self.exit_reports: Dict[str, Optional[dict]] = {}

    def start(self, ready_timeout_s: float = 180.0) -> "Fleet":
        t0 = time.monotonic()
        for w in self.workers.values():
            w.start()
        for w in self.workers.values():
            left = max(1.0, ready_timeout_s - (time.monotonic() - t0))
            try:
                w.wait_ready(timeout_s=left)
            except RuntimeError:
                self.stop(timeout_s=10.0)
                raise
        if not self._external_proxy:
            backends = [Backend(name, "127.0.0.1", w.port)
                        for name, w in self.workers.items()]
            self.proxy = EdgeProxy(backends, log=self._log,
                                   **self._proxy_kwargs).start()
        return self

    def add_worker(self, spec: WorkerSpec, *,
                   ready_timeout_s: float = 180.0,
                   stderr_dir: Optional[str] = None,
                   env: Optional[Dict[str, str]] = None) -> str:
        """Scale-up: boot one NEW worker and route to it only once it
        is genuinely warm (the PR-18 "cold stream starts on scale-up
        workers" remainder).

        The ordering is the contract: the worker boots, runs its full
        warmup — including, with ``spec.warm_streams``, the in-process
        stream-fit warm pass (cmd_serve's ``--warm-streams``: the
        fit-stage programs are NOT in the AOT lattice, per the PR-18
        dead-end, so the worker exercises one synthetic stream before
        printing its ready line) — and ONLY THEN is handed to
        ``proxy.add_backend``, which replays every known specialize
        before traffic can land. A fresh worker's first real frame
        pays zero compiles; test_fleet.py pins it via /metrics.

        Returns the new worker's name (``w<N>``, continuing the boot
        numbering)."""
        if self.proxy is None:
            raise RuntimeError("fleet is not started")
        env = self._env if env is None else env
        stderr_dir = (self._stderr_dir if stderr_dir is None
                      else stderr_dir)
        i = len(self.workers)
        while f"w{i}" in self.workers:
            i += 1
        name = f"w{i}"
        stderr_path = (os.path.join(stderr_dir, f"{name}.stderr")
                       if stderr_dir else None)
        w = WorkerProc(name, spec, env=env, stderr_path=stderr_path,
                       log=self._log)
        self.workers[name] = w
        try:
            w.start().wait_ready(timeout_s=ready_timeout_s)
        except RuntimeError:
            del self.workers[name]
            raise
        self.proxy.add_backend(Backend(name, "127.0.0.1", w.port))
        self._log(f"fleet: added worker {name} on port {w.port}")
        return name

    def kill_worker(self, name: str) -> None:
        """Chaos: SIGKILL one worker. The proxy discovers the death
        through its breaker / mid-frame failover — nothing is told in
        advance, which is the point of the drill."""
        self.workers[name].kill()
        self.exit_reports[name] = None

    def drain_worker(self, name: str, *,
                     migrate_timeout_s: float = 10.0,
                     term_timeout_s: float = 30.0) -> dict:
        """Rolling deploy: migrate the worker's proxied streams to
        siblings (bounded), then SIGTERM it so its own drain closes
        any remaining local state and prints the exit line."""
        if self.proxy is None:
            raise RuntimeError("fleet is not started")
        report = self.proxy.drain_backend(
            name, timeout_s=migrate_timeout_s)
        self.exit_reports[name] = self.workers[name].terminate(
            timeout_s=term_timeout_s)
        return report

    def stop(self, timeout_s: float = 30.0) -> Dict[str, Optional[dict]]:
        if self.proxy is not None:
            try:
                self.proxy.drain(timeout_s=min(10.0, timeout_s))
            except Exception:  # noqa: BLE001 — teardown must finish
                pass
        for name, w in self.workers.items():
            if name not in self.exit_reports or (
                    self.exit_reports[name] is None and w.alive()):
                self.exit_reports[name] = w.terminate(
                    timeout_s=timeout_s)
        return dict(self.exit_reports)


class FleetSupervisor:
    """The self-healing daemon over one :class:`Fleet` (PR 20).

    Detection is two-channel, both facts the worker contract already
    emits: (1) PROCESS DEATH — ``poll()`` says the worker is gone; the
    parsed exit line (present = it drained politely, absent = it was
    killed/crashed) classifies the death in the heal ledger. (2)
    UNRESPONSIVENESS — a live process whose ``/healthz`` stops
    answering (a partitioned/wedged worker): consecutive probe
    failures run through a per-worker ``runtime.health.CircuitBreaker``
    (``failure_threshold`` consecutive to trip, the same bounded +
    classified discipline as every other breaker in the repo — never
    the r3 bare-retry loop), and a tripped breaker is a death; the
    remains get SIGKILL (the only signal a C-level wedge cannot dodge,
    CLAUDE.md) before the replacement boots.

    The HEAL is the existing scale-up path with the port pinned: the
    replacement boots from the dead worker's own spec
    (``WorkerSpec.with_port`` — same AOT lattice dir, same
    ``--warm-streams``), runs its FULL warmup before printing ready
    (zero jit compiles on its first real frame, the PR-18 contract),
    and only then re-enters routing — ``proxy.add_backend`` for an
    in-process proxy (specialize replay included), or simply by
    BINDING THE SAME PORT when the proxy is a separate process
    (:class:`ProxyPair`), whose breaker re-probe rediscovers the
    backend with no wiring call. MTTR (detection -> routed) is
    recorded per heal.

    RESTART-STORM SUPPRESSION: restart attempts draw on a shared
    budget of ``restart_budget`` per sliding ``budget_window_s``. A
    death arriving with the budget exhausted — or a worker whose OWN
    failed heals exhausted it — DEGRADES: the worker is abandoned
    (fleet serves with fewer workers), an incident is recorded, and it
    is never retried. Flapping is structurally impossible: every boot
    attempt consumes budget whether or not it succeeds.

    Locking: ``_lock`` guards the ledger/counters/budget ONLY; all
    blocking work (probes, kills, boots) runs outside it on the
    supervisor thread, and ``load()`` is a single-hold snapshot (the
    torn-telemetry rule). Stop the supervisor BEFORE a planned drain /
    ``fleet.stop()`` — a polite operator-initiated exit is
    indistinguishable from a death by design (the exit line says how,
    not why)."""

    def __init__(self, fleet: Fleet, *,
                 poll_interval_s: float = 0.05,
                 probe_interval_s: float = 0.25,
                 probe_timeout_s: float = 2.0,
                 failure_threshold: int = 3,
                 restart_budget: int = 3,
                 budget_window_s: float = 60.0,
                 ready_timeout_s: float = 180.0,
                 spec_factory: Optional[Callable[[str, WorkerSpec],
                                                 WorkerSpec]] = None,
                 log: Optional[Callable[[str], None]] = None):
        if restart_budget < 1:
            raise ValueError(
                f"restart_budget must be >= 1, got {restart_budget}")
        self._fleet = fleet
        self._poll_interval_s = float(poll_interval_s)
        self._probe_interval_s = float(probe_interval_s)
        self._probe_timeout_s = float(probe_timeout_s)
        self._failure_threshold = int(failure_threshold)
        self._restart_budget = int(restart_budget)
        self._budget_window_s = float(budget_window_s)
        self._ready_timeout_s = float(ready_timeout_s)
        # Test/drill hook: how to build the replacement spec from the
        # dead worker's (name, spec). Default = same spec, same port.
        self._spec_factory = spec_factory
        self._log = log or (lambda m: None)
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._breakers: Dict[str, object] = {}
        self._last_probe: Dict[str, float] = {}
        self._abandoned: set = set()
        self._restart_times: List[float] = []   # budget window, pruned
        # -- ledger (under _lock) --
        self.heals: List[dict] = []
        self.incidents: List[dict] = []
        self.restarts = 0            # successful replacement boots
        self.restarts_failed = 0     # boot attempts that did not ready
        self.deaths_detected = 0
        self.probe_failures = 0

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "FleetSupervisor":
        if self._thread is not None:
            raise RuntimeError("supervisor already started")
        self._thread = threading.Thread(
            target=self._run, name="mano-fleet-supervisor", daemon=True)
        self._thread.start()
        return self

    def stop(self, timeout_s: float = 10.0) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout_s)

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self._sweep()
            except Exception as e:  # noqa: BLE001 — the daemon survives
                self._log(f"supervisor sweep failed "
                          f"({type(e).__name__}: {e})")
            self._stop.wait(self._poll_interval_s)

    # ------------------------------------------------------------ detection
    def _breaker(self, name: str):
        from mano_hand_tpu.runtime.health import CircuitBreaker

        br = self._breakers.get(name)
        if br is None:
            br = CircuitBreaker(
                failure_threshold=self._failure_threshold,
                probe_interval_s=self._probe_interval_s,
                probe_backoff=2.0,
                probe_interval_cap_s=8.0 * self._probe_interval_s,
                respect_priority_claim=False,
                probe=lambda: False)   # the sweep IS the prober
            self._breakers[name] = br
        return br

    def _healthz_ok(self, w: WorkerProc) -> bool:
        from mano_hand_tpu.edge.client import EdgeClient

        port = w.port
        if port is None:
            return False
        try:
            h = EdgeClient("127.0.0.1", port,
                           timeout_s=self._probe_timeout_s).healthz()
            return bool(h.get("ok", False))
        except Exception:  # noqa: BLE001 — any failure is a failed probe
            return False

    def _sweep(self) -> None:
        now = time.monotonic()
        for name, w in list(self._fleet.workers.items()):
            if self._stop.is_set():
                return
            with self._lock:
                if name in self._abandoned:
                    continue
            if not w.alive():
                self._heal(name, w,
                           reason=("clean_exit"
                                   if w.exit_report is not None
                                   else "exit"))
                continue
            if now - self._last_probe.get(name, 0.0) \
                    < self._probe_interval_s:
                continue
            self._last_probe[name] = now
            br = self._breaker(name)
            if self._healthz_ok(w):
                br.record_success()
                continue
            with self._lock:
                self.probe_failures += 1
            from mano_hand_tpu.runtime import health as health_mod

            if br.record_failure() == health_mod.DOWN:
                # Consecutive-failure threshold crossed: the worker is
                # partitioned/wedged. SIGKILL the remains first — a
                # half-dead process must not hold the port the
                # replacement needs.
                self._heal(name, w, reason="probe")

    # ----------------------------------------------------------------- heal
    def _budget_left(self, now: float) -> int:
        """Caller holds ``_lock``. Prunes the sliding window."""
        cutoff = now - self._budget_window_s
        self._restart_times = [t for t in self._restart_times
                               if t > cutoff]
        return self._restart_budget - len(self._restart_times)

    def _heal(self, name: str, dead: WorkerProc, reason: str) -> None:
        fleet = self._fleet
        t0 = time.monotonic()
        with self._lock:
            self.deaths_detected += 1
            if self._budget_left(t0) <= 0:
                self._abandoned.add(name)
                inc = {"worker": name, "reason": reason,
                       "incident": "restart budget exhausted "
                                   f"({self._restart_budget} per "
                                   f"{self._budget_window_s}s window); "
                                   "degraded to fewer workers",
                       "t_mono": round(t0, 3)}
                self.incidents.append(inc)
            else:
                self._restart_times.append(t0)
                inc = None
        self._log(f"supervisor: worker {name} dead ({reason})"
                  + ("; budget exhausted — degrading" if inc else
                     "; healing"))
        port = dead.port
        # SIGKILL the remains in every path (idempotent on a reaped
        # process): a partitioned worker still holds its socket.
        dead.kill()
        if fleet.proxy is not None:
            try:
                fleet.proxy.remove_backend(name)
            except KeyError:
                pass     # a previous heal round already removed it
        if inc is not None:
            self._log(f"supervisor: incident — {inc['incident']}")
            return
        spec = dead.spec
        if self._spec_factory is not None:
            spec = self._spec_factory(name, spec)
        elif port is not None:
            spec = spec.with_port(port)
        stderr_path = None
        if fleet._stderr_dir:
            stderr_path = os.path.join(
                fleet._stderr_dir, f"{name}.heal.stderr")
        repl = WorkerProc(name, spec, env=fleet._env,
                          stderr_path=stderr_path, log=self._log)
        try:
            repl.start().wait_ready(timeout_s=self._ready_timeout_s)
        except RuntimeError as e:
            with self._lock:
                self.restarts_failed += 1
            self._log(f"supervisor: replacement {name} failed to boot "
                      f"({e}); budget permitting, the next sweep "
                      "retries")
            # Leave the dead WorkerProc in place: the next sweep sees
            # it dead and re-enters _heal — bounded by the budget.
            return
        fleet.workers[name] = repl
        self._breakers.pop(name, None)       # fresh breaker, fresh state
        if fleet.proxy is not None:
            fleet.proxy.add_backend(
                Backend(name, "127.0.0.1", repl.port))
        # else: ProxyPair mode — the replacement bound the dead
        # worker's port, and the proxy's backend breaker re-probe
        # re-admits it with no wiring call.
        mttr_ms = (time.monotonic() - t0) * 1e3
        with self._lock:
            self.restarts += 1
            self.heals.append({
                "worker": name, "reason": reason,
                "port": repl.port, "pid": repl.pid,
                "mttr_ms": round(mttr_ms, 1),
            })
        self._log(f"supervisor: healed {name} on port {repl.port} in "
                  f"{mttr_ms:.0f} ms ({reason})")

    # ------------------------------------------------------------ telemetry
    def load(self) -> dict:
        """``{"fleet": {...}}`` — every ledger field from ONE ``_lock``
        hold, so the counts always equal the lists beside them (the
        torn-read hammer in tests/test_selfheal.py spins on exactly
        these invariants)."""
        now = time.monotonic()
        with self._lock:
            return {"fleet": {
                "restarts": self.restarts,
                "restarts_failed": self.restarts_failed,
                "deaths_detected": self.deaths_detected,
                "probe_failures": self.probe_failures,
                "incidents": len(self.incidents),
                "incident_log": [dict(i) for i in self.incidents],
                "heals": [dict(h) for h in self.heals],
                "mttr_ms": [h["mttr_ms"] for h in self.heals],
                "abandoned": sorted(self._abandoned),
                "budget": {
                    "restart_budget": self._restart_budget,
                    "window_s": self._budget_window_s,
                    "left": max(0, self._budget_left(now)),
                },
            }}


# --------------------------------------------------------------------------
# Active/standby proxy pair (PR 20): the EdgeProxy's own availability.
# --------------------------------------------------------------------------

class ProxySpec:
    """The knobs one ``mano proxy`` process boots with. ``backends``
    is a sequence of ``(name, host, port)`` — with PR-20's fixed
    worker ports the list is STATIC across worker heals, which is what
    lets a standby hold the same list the active used."""

    def __init__(self, *, port: int, lock_path: str,
                 backends: Sequence, drain_timeout_s: float = 10.0,
                 upstream_timeout_s: float = 300.0,
                 extra: Sequence[str] = ()):
        self.port = int(port)
        self.lock_path = str(lock_path)
        self.backends = [(str(n), str(h), int(p))
                         for (n, h, p) in backends]
        self.drain_timeout_s = float(drain_timeout_s)
        self.upstream_timeout_s = float(upstream_timeout_s)
        self.extra = tuple(extra)

    def argv(self) -> List[str]:
        cmd = [sys.executable, "-m", "mano_hand_tpu.cli", "proxy",
               "--port", str(self.port),
               "--lock", self.lock_path,
               "--drain-timeout-s", repr(self.drain_timeout_s),
               "--upstream-timeout-s", repr(self.upstream_timeout_s)]
        for n, h, p in self.backends:
            cmd += ["--backend", f"{n}={h}:{p}"]
        cmd += list(self.extra)
        return cmd


class ProxyProc:
    """One supervised ``mano proxy`` process (cmd_proxy's stdout
    contract): a ``{"proxy": {...}}`` ready line at spawn (role
    ``standby``), a ``{"proxy_event": {"event": "active", ...}}`` line
    when the flock is won and the service port is bound, and a
    ``{"proxy_exit": {...}}`` line after a polite drain. Same
    SIGKILL-backstop discipline as :class:`WorkerProc`."""

    def __init__(self, name: str, spec: ProxySpec, *,
                 env: Optional[Dict[str, str]] = None,
                 stderr_path: Optional[str] = None,
                 log: Optional[Callable[[str], None]] = None):
        self.name = name
        self.spec = spec
        self._env = env
        self._stderr_path = stderr_path
        self._log = log or (lambda m: None)
        self._proc: Optional[subprocess.Popen] = None
        self._reader: Optional[threading.Thread] = None
        self._stderr_f = None
        self._ready = threading.Event()
        self._active = threading.Event()
        self.ready_info: Optional[dict] = None
        self.active_info: Optional[dict] = None
        self.exit_report: Optional[dict] = None
        self.events: List[dict] = []
        self.stdout_lines: List[str] = []
        self.returncode: Optional[int] = None

    def start(self) -> "ProxyProc":
        if self._proc is not None:
            return self
        env = dict(os.environ)
        if self._env:
            env.update(self._env)
        if self._stderr_path:
            self._stderr_f = open(self._stderr_path, "ab")
            stderr = self._stderr_f
        else:
            stderr = subprocess.DEVNULL
        self._proc = subprocess.Popen(
            self.spec.argv(), stdout=subprocess.PIPE, stderr=stderr,
            env=env, start_new_session=True)
        self._reader = threading.Thread(
            target=self._drain_stdout, name=f"stdout-{self.name}",
            daemon=True)
        self._reader.start()
        return self

    def _drain_stdout(self) -> None:
        proc = self._proc
        try:
            for raw in proc.stdout:
                line = raw.decode("utf-8", "replace").rstrip("\n")
                self.stdout_lines.append(line)
                try:
                    d = json.loads(line)
                except ValueError:
                    continue
                if "proxy" in d:
                    self.ready_info = d["proxy"]
                    self._ready.set()
                elif "proxy_event" in d:
                    ev = d["proxy_event"]
                    self.events.append(ev)
                    if ev.get("event") == "active":
                        self.active_info = ev
                        self._active.set()
                elif "proxy_exit" in d:
                    self.exit_report = d["proxy_exit"]
        except (OSError, ValueError):
            pass
        finally:
            self._ready.set()
            self._active.set()      # never strand a takeover waiter

    @property
    def pid(self) -> Optional[int]:
        return None if self._proc is None else self._proc.pid

    def alive(self) -> bool:
        return self._proc is not None and self._proc.poll() is None

    def is_active(self) -> bool:
        return self.alive() and self.active_info is not None

    def wait_ready(self, timeout_s: float = 60.0) -> "ProxyProc":
        if not self._ready.wait(timeout=timeout_s) \
                or self.ready_info is None:
            rc = self._proc.poll() if self._proc else None
            self.kill()
            raise RuntimeError(
                f"proxy {self.name} not ready within {timeout_s}s "
                f"(rc={rc}); stdout: {self.stdout_lines[-3:]}")
        return self

    def wait_active(self, timeout_s: float = 60.0) -> "ProxyProc":
        """Block until THIS proc won the flock and bound the service
        port (its ``active`` event) — or died trying."""
        if not self._active.wait(timeout=timeout_s) \
                or self.active_info is None or not self.alive():
            raise RuntimeError(
                f"proxy {self.name} did not become active within "
                f"{timeout_s}s (alive={self.alive()}); stdout: "
                f"{self.stdout_lines[-3:]}")
        return self

    def kill(self) -> None:
        if self._proc is None:
            return
        try:
            self._proc.kill()
        except OSError:
            pass
        self._finish(join_timeout_s=10.0)

    def terminate(self, timeout_s: float = 30.0) -> Optional[dict]:
        if self._proc is None:
            return None
        if self._proc.poll() is None:
            try:
                self._proc.send_signal(signal.SIGTERM)
            except OSError:
                pass
        deadline = time.monotonic() + timeout_s
        try:
            self._proc.wait(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            self._log(f"proxy {self.name}: SIGTERM deadline hit — "
                      "SIGKILL backstop")
            try:
                self._proc.kill()
            except OSError:
                pass
        self._finish(join_timeout_s=max(1.0,
                                        deadline - time.monotonic()))
        return self.exit_report

    def _finish(self, join_timeout_s: float) -> None:
        try:
            self._proc.wait(timeout=join_timeout_s)
        except subprocess.TimeoutExpired:
            pass
        self.returncode = self._proc.poll()
        if self._reader is not None:
            self._reader.join(timeout=join_timeout_s)
        if self._stderr_f is not None:
            try:
                self._stderr_f.close()
            except OSError:
                pass
            self._stderr_f = None


class ProxyPair:
    """Active/standby ``mano proxy`` pair behind one flock-arbitered
    service port (the ``DeviceLock`` pattern at socket level).

    Both procs boot from ONE :class:`ProxySpec` (same port, same lock
    file, same static backend list). Whoever wins ``flock(LOCK_EX)``
    binds the service port and serves; the loser parks in cmd_proxy's
    bounded-step SIGTERM-interruptible ``LOCK_NB`` poll (a C-level
    ``LOCK_EX`` wait would make the standby unkillable politely).
    When the active dies — SIGKILL included — the kernel RELEASES the
    flock with the process, the standby acquires it, increments the
    takeover generation in the lock file, binds the SAME port, and
    rebuilds routing from the workers' ``/healthz`` (cmd_proxy's
    resync). In-flight streams are NOT carried over: clients hold the
    PR-18 last-confirmed-pose protocol (``edge/client.py:
    ResilientStream``), reconnect to the same address, and resume via
    ``resume_pose`` with continuous frame numbering — the takeover
    loses no stream."""

    def __init__(self, spec: ProxySpec, *,
                 env: Optional[Dict[str, str]] = None,
                 stderr_dir: Optional[str] = None,
                 log: Optional[Callable[[str], None]] = None):
        self.spec = spec
        self._log = log or (lambda m: None)
        self.procs: List[ProxyProc] = []
        for i in range(2):
            name = f"p{i}"
            stderr_path = (os.path.join(stderr_dir, f"{name}.stderr")
                           if stderr_dir else None)
            self.procs.append(ProxyProc(
                name, spec, env=env, stderr_path=stderr_path,
                log=self._log))
        self.exit_reports: Dict[str, Optional[dict]] = {}

    @property
    def port(self) -> int:
        """The stable service port (survives takeover)."""
        return self.spec.port

    def start(self, timeout_s: float = 60.0) -> "ProxyPair":
        t0 = time.monotonic()
        for p in self.procs:
            p.start()
        for p in self.procs:
            left = max(1.0, timeout_s - (time.monotonic() - t0))
            p.wait_ready(timeout_s=left)
        # Exactly one wins the flock; wait until it is serving.
        self.wait_active(timeout_s=max(
            1.0, timeout_s - (time.monotonic() - t0)))
        return self

    def active(self) -> Optional[ProxyProc]:
        """The proc currently holding the flock (None mid-takeover).
        The LAST active event wins: a standby that took over has a
        newer event than the corpse it replaced."""
        live = [p for p in self.procs if p.is_active()]
        if not live:
            return None
        return max(live, key=lambda p: p.active_info.get("takeovers", 0))

    def wait_active(self, timeout_s: float = 60.0) -> ProxyProc:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            p = self.active()
            if p is not None:
                return p
            time.sleep(0.02)
        raise RuntimeError(
            f"no active proxy within {timeout_s}s "
            f"(alive={[p.alive() for p in self.procs]})")

    def kill_active(self) -> str:
        """Chaos: SIGKILL the active proxy; returns its name. The
        standby discovers the death through the kernel's flock
        release — nothing is told in advance."""
        p = self.wait_active(timeout_s=10.0)
        p.kill()
        self.exit_reports[p.name] = None
        return p.name

    def stop(self, timeout_s: float = 30.0) -> Dict[str, Optional[dict]]:
        """Polite teardown of both procs: the active drains and prints
        its exit line; the standby's ``LOCK_NB`` poll exits on SIGTERM
        (both bounded, SIGKILL backstop)."""
        for p in self.procs:
            if p.name not in self.exit_reports or (
                    self.exit_reports[p.name] is None and p.alive()):
                self.exit_reports[p.name] = p.terminate(
                    timeout_s=timeout_s)
        return dict(self.exit_reports)
