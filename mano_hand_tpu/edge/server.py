"""The network edge: a thin asyncio HTTP front-end over ServingEngine.

Fourteen PRs of serving machinery — bucketed coalescing, admission
tiers, deadlines, streams, lanes, precision tiers, SLO burn rates — are
all reachable only via in-process ``submit()``. This process boundary
is the last step from "serving library" to "service", and the forcing
function that keeps every internal API honest about serialization
(ROADMAP item 8): everything that crosses this module is bytes.

The server is deliberately THIN: every decision it makes is a mapping
of machinery that already exists.

* **One-shot requests** (``POST /v1/forward``): the PR-5 tier and TTL
  ride headers (``X-Mano-Priority``, ``X-Mano-Deadline-S``) straight
  into ``submit(priority=, deadline_s=)``; the response is the verts
  array, losslessly encoded (edge/protocol.py) so the wire result is
  BIT-identical to the in-process future's.
* **Backpressure**: a ``ServingError(kind="shed")`` maps to 429 with a
  per-tier ``Retry-After`` derived from ``load()`` — the O(µs)
  admission decision stays the engine's; the edge only translates it.
* **Streams** (``/v1/stream`` + ``Upgrade: mano-stream/1`` -> 101):
  the PR-12 open/frame/close protocol over one persistent connection,
  newline-delimited JSON both ways. The socket IS the session: a
  client disconnect cancels the in-flight frame future (the PR-13
  caller-cancellation path — terminal kind ``cancelled``) and closes
  the session, so an abandoned user never pins engine capacity.
* **Graceful drain** (SIGTERM -> ``drain()``): new connections are
  refused (the listener closes first), fully-received in-flight
  requests resolve, idle keep-alive connections are swept, and the
  engine runs its PR-3/5 ``stop(timeout_s=)`` sweep — every
  outstanding future resolves, every stream span closes, bounded by
  the timeout (monotonic arithmetic throughout).
* **Observability**: ``GET /metrics`` serves the PR-9 Prometheus text
  export of the engine's registry; ``GET /healthz`` derives liveness
  from dispatcher/breaker/lane state; every 5xx response carries a
  PR-8 flight-record capture in its body — the black box arrives WITH
  the incident, not after it.

Blocking discipline: the event loop never waits on the engine.
``submit()`` is O(µs) host bookkeeping and is called inline; future
resolution is awaited via ``asyncio.wrap_future``; anything that can
touch the device or run solver math (``specialize``, ``open_stream``,
``submit_frame``) runs in the default executor. HTTP parsing is
hand-rolled over asyncio streams (stdlib-only — the container bakes no
HTTP framework, and the protocol surface is deliberately tiny).

Multi-worker coexistence: the server takes NO device lock itself —
`mano serve` wraps it in ``utils.devicelock.DeviceLock(role="server")``
(a SHARED flock: N workers coexist, the driver bench's exclusive lock
and priority claim still win — see devicelock.py).
"""

from __future__ import annotations

import asyncio
import json
import threading
import time
from typing import Callable, Optional

import numpy as np

from mano_hand_tpu.edge import protocol as proto
from mano_hand_tpu.serving.engine import ServingError

#: Bound on request bodies (arrays are small: a 1024-row pose batch is
#: ~200 KB encoded) — a runaway body must fail fast, not grow memory.
MAX_BODY_BYTES = 8 << 20

#: asyncio stream readline limit (request line / one NDJSON frame).
_LINE_LIMIT = 1 << 20


class _Pushback:
    """Tiny buffered reader: the disconnect watcher reads one byte
    ahead of the parser; a byte that turns out to be the next
    request's first byte is pushed back instead of eaten."""

    def __init__(self, reader: asyncio.StreamReader):
        self.r = reader
        self.buf = b""

    async def readline(self) -> bytes:
        if self.buf:
            head, self.buf = self.buf, b""
            if b"\n" in head:               # a full buffered line
                i = head.index(b"\n") + 1
                self.buf = head[i:]
                return head[:i]
            return head + await self.r.readline()
        return await self.r.readline()

    async def readexactly(self, n: int) -> bytes:
        if self.buf:
            head, self.buf = self.buf[:n], self.buf[n:]
            if len(head) == n:
                return head
            return head + await self.r.readexactly(n - len(head))
        return await self.r.readexactly(n)

    async def read1(self) -> bytes:
        if self.buf:
            b, self.buf = self.buf[:1], self.buf[1:]
            return b
        return await self.r.read(1)

    def push(self, data: bytes) -> None:
        self.buf = data + self.buf


class _Request:
    __slots__ = ("method", "path", "headers", "body")

    def __init__(self, method, path, headers, body):
        self.method = method
        self.path = path
        self.headers = headers      # lower-cased keys
        self.body = body


async def write_response(writer, status: int, body,
                         *, content_type: str = "application/json",
                         extra_headers: Optional[dict] = None,
                         close: bool = False) -> None:
    """Serialize one HTTP/1.1 response (shared by EdgeServer and the
    PR-18 proxy — one implementation owns the bytes)."""
    payload = (body if isinstance(body, (bytes, bytearray))
               else proto.dumps(body))
    head = [f"HTTP/1.1 {status} {proto.reason(status)}",
            f"Content-Type: {content_type}",
            f"Content-Length: {len(payload)}"]
    for k, v in (extra_headers or {}).items():
        head.append(f"{k}: {v}")
    if close:
        head.append("Connection: close")
    writer.write(("\r\n".join(head) + "\r\n\r\n").encode("latin-1")
                 + bytes(payload))
    await writer.drain()


async def read_request(rd: _Pushback, writer, *,
                       max_body_bytes: int = MAX_BODY_BYTES,
                       draining: bool = False) -> Optional[_Request]:
    """Parse one HTTP/1.1 request off an upgraded-capable connection;
    answers the malformed cases itself (400/413) and returns None when
    the connection is done (shared by EdgeServer and the proxy)."""
    try:
        line = await rd.readline()
    except (ValueError, asyncio.LimitOverrunError):
        await write_response(writer, 400, proto.error_body(
            "bad_request", "request line too long"), close=draining)
        return None
    if not line:
        return None                 # clean EOF between requests
    try:
        method, path, _version = line.decode(
            "latin-1").strip().split(" ", 2)
    except ValueError:
        await write_response(writer, 400, proto.error_body(
            "bad_request", "malformed request line"), close=draining)
        return None
    headers = {}
    while True:
        h = await rd.readline()
        if h in (b"\r\n", b"\n"):
            break
        if not h:
            return None             # EOF mid-headers: client gone
        if len(headers) > 128:
            await write_response(writer, 400, proto.error_body(
                "bad_request", "too many headers"), close=draining)
            return None
        name, _, value = h.decode("latin-1").partition(":")
        headers[name.strip().lower()] = value.strip()
    body = b""
    if headers.get("transfer-encoding"):
        await write_response(writer, 400, proto.error_body(
            "bad_request", "chunked bodies are not supported"),
            close=draining)
        return None
    clen = headers.get("content-length")
    if clen:
        try:
            n = int(clen)
        except ValueError:
            n = -1
        if n < 0 or n > max_body_bytes:
            await write_response(writer, 413, proto.error_body(
                "bad_request",
                f"body of {clen} bytes exceeds the "
                f"{max_body_bytes}-byte bound"), close=draining)
            return None
        body = await rd.readexactly(n)
    return _Request(method, path, headers, body)


class EdgeServer:
    """Asyncio HTTP front-end over one ``ServingEngine``.

    Runs its event loop in a daemon thread (``start()``); ``drain()``
    is the SIGTERM path and is callable from any thread. ``port=0``
    binds an ephemeral port (read ``self.port`` after ``start()``) —
    the loopback-drill/test form.

    The engine is caller-owned: the server starts it implicitly via
    the first ``submit`` and stops it ONLY inside ``drain()`` (the
    documented shutdown sweep). ``registry`` defaults to a fresh
    ``obs.metrics.engine_registry(engine)``.
    """

    def __init__(self, engine, host: str = "127.0.0.1", port: int = 0,
                 *, registry=None, drain_timeout_s: float = 10.0,
                 max_body_bytes: int = MAX_BODY_BYTES,
                 retry_after_source: Optional[Callable] = None,
                 warm_streams: Optional[bool] = None,
                 log: Optional[Callable[[str], None]] = None):
        self._engine = engine
        # PR 20: did this worker pre-warm its stream path before
        # declaring ready (cmd_serve --warm-streams)? Tri-state fact
        # surfaced on /healthz so the proxy can keep NEW stream opens
        # off a cold scale-up worker. None = the owner never said
        # (embedded/test servers) and the key is omitted from healthz.
        self._warm_streams = warm_streams
        self.host = host
        self.port = int(port)           # rewritten to the bound port
        self._registry = registry
        self.drain_timeout_s = float(drain_timeout_s)
        self.max_body_bytes = int(max_body_bytes)
        # Closed-loop control (PR 19): an optional
        # ``(tier, load) -> Optional[int]`` callback (the controller's
        # ``retry_after_for``) that OWNS the 429 Retry-After when it
        # returns an int; None (no controller, no opinion, or crashed)
        # falls back to the static ``protocol.retry_after_s`` formula —
        # the wire degrades to today's behavior exactly.
        self._retry_after_source = retry_after_source
        self._log = log or (lambda m: None)
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._server: Optional[asyncio.AbstractServer] = None
        self._thread: Optional[threading.Thread] = None
        self._ready = threading.Event()
        self._boot_error: Optional[BaseException] = None
        self._conn_tasks: set = set()
        # Fully-received requests currently being served (the drain
        # wait's definition of "in flight"); loop-thread-only writes.
        self._active_requests = 0
        self._draining = False
        self._drained = False
        self._t0 = time.monotonic()
        self.requests_served = 0

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "EdgeServer":
        if self._thread is not None:
            return self
        self._thread = threading.Thread(
            target=self._run, name="mano-edge", daemon=True)
        self._thread.start()
        if not self._ready.wait(timeout=30.0):
            raise RuntimeError("edge server failed to bind within 30s")
        if self._boot_error is not None:
            raise RuntimeError(
                f"edge server failed to start: {self._boot_error}")
        return self

    def _run(self) -> None:
        loop = asyncio.new_event_loop()
        self._loop = loop
        try:
            loop.run_until_complete(self._serve_main())
        except BaseException as e:  # noqa: BLE001 — surface via start()
            self._boot_error = e
            self._ready.set()
        finally:
            try:
                loop.close()
            except Exception:  # noqa: BLE001
                pass

    async def _serve_main(self) -> None:
        self._server = await asyncio.start_server(
            self._handle, self.host, self.port, limit=_LINE_LIMIT)
        self.port = self._server.sockets[0].getsockname()[1]
        self._stop_event = asyncio.Event()
        self._ready.set()
        self._log(f"edge listening on {self.host}:{self.port}")
        await self._stop_event.wait()

    def drain(self, timeout_s: Optional[float] = None) -> dict:
        """The SIGTERM path: refuse new connections, resolve in-flight
        requests, sweep idle connections, run the engine's
        ``stop(timeout_s=)`` sweep, stop the loop. Callable from any
        thread; idempotent (a second drain reports the first's
        outcome). Returns a small report dict for the caller's exit
        line."""
        if timeout_s is None:
            timeout_s = self.drain_timeout_s
        if self._loop is None or self._drained:
            return {"drained": self._drained, "already": True}
        t0 = time.monotonic()
        fut = asyncio.run_coroutine_threadsafe(
            self._drain_async(float(timeout_s)), self._loop)
        try:
            report = fut.result(timeout=timeout_s + 30.0)
        except Exception as e:  # noqa: BLE001 — report, never hang
            report = {"drained": False,
                      "error": f"{type(e).__name__}: {e}"}
        self._drained = True
        if self._thread is not None:
            self._thread.join(timeout=10.0)
        report["wall_s"] = round(time.monotonic() - t0, 4)
        return report

    async def _drain_async(self, timeout_s: float) -> dict:
        deadline = time.monotonic() + timeout_s
        self._draining = True
        srv = self._server
        if srv is not None:
            srv.close()                 # new connections refused NOW
            await srv.wait_closed()
        # In-flight (fully received) requests get the rest of the
        # window to resolve; idle keep-alive connections are parked in
        # a readline and cannot "finish" — they are swept after.
        while self._active_requests > 0 and time.monotonic() < deadline:
            await asyncio.sleep(0.005)
        inflight_resolved = self._active_requests == 0
        for t in list(self._conn_tasks):
            if not t.done():
                t.cancel()
        if self._conn_tasks:
            await asyncio.wait(list(self._conn_tasks), timeout=1.0)
        loop = asyncio.get_running_loop()
        eng_timeout = max(0.1, deadline - time.monotonic())
        # The engine's own drain sweep (PR 3/5): blocking, so it runs
        # in the executor — the loop stays responsive to the task
        # cancellations above.
        await loop.run_in_executor(
            None, lambda: self._engine.stop(timeout_s=eng_timeout))
        self._stop_event.set()
        return {
            "drained": True,
            "inflight_resolved": inflight_resolved,
            "requests_served": self.requests_served,
            "within_timeout": time.monotonic() <= deadline,
        }

    # ----------------------------------------------------------- connection
    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        task = asyncio.current_task()
        self._conn_tasks.add(task)
        rd = _Pushback(reader)
        try:
            while True:
                req = await self._read_request(rd, writer)
                if req is None:
                    break
                self._active_requests += 1
                try:
                    keep = await self._dispatch(req, rd, writer)
                finally:
                    self._active_requests -= 1
                    self.requests_served += 1
                if not keep or self._draining:
                    break
        except (asyncio.CancelledError, ConnectionError,
                asyncio.IncompleteReadError):
            pass
        except Exception as e:  # noqa: BLE001 — one bad conn != the server
            self._log(f"edge connection error: {type(e).__name__}: {e}")
        finally:
            self._conn_tasks.discard(task)
            try:
                writer.close()
            except Exception:  # noqa: BLE001
                pass

    async def _read_request(self, rd: _Pushback,
                            writer) -> Optional[_Request]:
        return await read_request(
            rd, writer, max_body_bytes=self.max_body_bytes,
            draining=self._draining)

    async def _respond(self, writer, status: int, body,
                       *, content_type: str = "application/json",
                       extra_headers: Optional[dict] = None,
                       close: bool = False) -> None:
        await write_response(
            writer, status, body, content_type=content_type,
            extra_headers=extra_headers,
            close=close or self._draining)

    # ------------------------------------------------------------- routing
    async def _dispatch(self, req: _Request, rd: _Pushback,
                        writer) -> bool:
        """Serve one request; returns False to close the connection."""
        if self._draining:
            await self._respond(writer, 503, proto.error_body(
                "shutdown", "edge is draining; connection closing"),
                close=True)
            return False
        route = (req.method, req.path.split("?", 1)[0])
        try:
            if route == ("GET", "/healthz"):
                return await self._h_healthz(writer)
            if route == ("GET", "/metrics"):
                return await self._h_metrics(writer)
            if route == ("POST", "/v1/forward"):
                return await self._h_forward(req, rd, writer)
            if route == ("POST", "/v1/specialize"):
                return await self._h_specialize(req, writer)
            if route[1] == "/v1/stream":
                if (req.headers.get("upgrade") or "").lower() \
                        != proto.STREAM_UPGRADE:
                    await self._respond(writer, 400, proto.error_body(
                        "bad_request",
                        f"/v1/stream requires 'Upgrade: "
                        f"{proto.STREAM_UPGRADE}'"))
                    return True
                return await self._h_stream(rd, writer)
            status = 404 if route[1] not in (
                "/healthz", "/metrics", "/v1/forward",
                "/v1/specialize") else 405
            await self._respond(writer, status, proto.error_body(
                "bad_request", f"no route for {req.method} {req.path}"))
            return True
        except (ConnectionError, asyncio.IncompleteReadError):
            raise
        except asyncio.CancelledError:
            raise
        except Exception as e:  # noqa: BLE001 — 500 + flight, not a crash
            await self._respond(
                writer, 500, proto.error_body(
                    "error", f"{type(e).__name__}: {e}",
                    flight=self._flight(f"edge_500_{route[1]}")))
            return True

    def _flight(self, reason: str) -> Optional[dict]:
        """A trimmed PR-8 flight capture for a 5xx body (None without a
        tracer — the capture must never be the thing that fails)."""
        tr = self._engine.tracer
        if tr is None:
            return None
        try:
            from mano_hand_tpu.obs import flight_record

            return flight_record(tr, self._engine.counters,
                                 reason=reason, max_spans=8,
                                 max_events=32)
        except Exception:  # noqa: BLE001
            return None

    # ------------------------------------------------------------ handlers
    async def _h_healthz(self, writer) -> bool:
        eng = self._engine
        load = eng.load()
        failure = getattr(eng, "_failure", None)
        policy = getattr(eng, "_policy", None)
        breaker = getattr(policy, "breaker", None)
        lanes = load.get("lanes")
        status = ("draining" if self._draining
                  else ("failed" if failure is not None else "serving"))
        ok = status == "serving"
        degraded = False
        if lanes:
            healthy = lanes.get("healthy")
            if healthy == 0:
                ok = False
            elif healthy is not None and healthy < lanes.get("n_lanes", 0):
                degraded = True
        if breaker is not None and breaker.state != "healthy":
            degraded = True     # CPU failover still serves: degraded, up
        body = {
            "ok": ok,
            "status": status,
            "degraded": degraded,
            "uptime_s": round(time.monotonic() - self._t0, 3),
            "engine": {
                "outstanding": load.get("outstanding"),
                "queued": load.get("queued"),
                "max_queued": load.get("max_queued"),
                "admission": load.get("admission"),
                "failure": (None if failure is None else str(failure)),
            },
            "streams": {
                "active": (load.get("streams") or {}).get("active"),
                "frames_in_flight": (load.get("streams") or {}
                                     ).get("frames_in_flight"),
            },
            "lanes": (None if not lanes else {
                "n_lanes": lanes.get("n_lanes"),
                "healthy": lanes.get("healthy"),
            }),
            "breaker": None if breaker is None else breaker.state,
        }
        if self._warm_streams is not None:
            body["warm_streams"] = bool(self._warm_streams)
        await self._respond(writer, 200 if ok else 503, body)
        return True

    async def _h_metrics(self, writer) -> bool:
        reg = self._registry
        if reg is None:
            from mano_hand_tpu.obs.metrics import engine_registry

            reg = self._registry = engine_registry(self._engine)
        loop = asyncio.get_running_loop()
        # The scrape walks every collector (several one-lock-hold
        # snapshots); executor keeps the accept loop responsive.
        text = await loop.run_in_executor(None, reg.prometheus)
        await self._respond(writer, 200, text.encode("utf-8"),
                            content_type="text/plain; version=0.0.4")
        return True

    def _qos(self, req: _Request, body: dict):
        """(priority, deadline_s) from headers (body fields as the
        fallback — headers win so proxies can rewrite QoS)."""
        prio = req.headers.get(proto.PRIORITY_HEADER)
        if prio is None:
            prio = body.get("priority", 0)
        ddl = req.headers.get(proto.DEADLINE_HEADER)
        if ddl is None:
            ddl = body.get("deadline_s")
        return int(prio), (None if ddl in (None, "") else float(ddl))

    async def _h_forward(self, req: _Request, rd: _Pushback,
                         writer) -> bool:
        try:
            body = json.loads(req.body or b"{}")
            pose = proto.decode_array(body["pose"])
            shape = (proto.decode_array(body["shape"])
                     if body.get("shape") is not None else None)
            subject = body.get("subject")
            tier, deadline_s = self._qos(req, body)
        except (KeyError, ValueError, TypeError) as e:
            await self._respond(writer, 400, proto.error_body(
                "bad_request", f"malformed forward request: {e}"))
            return True
        try:
            fut = self._engine.submit(
                pose, shape, subject=subject, priority=tier,
                deadline_s=deadline_s)
        except ServingError as e:
            return await self._serving_error(writer, e, tier)
        except (ValueError, RuntimeError) as e:
            # Caller errors (bad shape, unknown subject) and a dead
            # dispatcher: the former 400, the latter 503.
            if isinstance(e, RuntimeError):
                await self._respond(writer, 503, proto.error_body(
                    "shutdown", str(e),
                    flight=self._flight("edge_submit_failed")))
            else:
                await self._respond(writer, 400, proto.error_body(
                    "bad_request", str(e)))
            return True
        verts, gone = await self._await_future(fut, rd, deadline_s)
        if gone:
            return False                # disconnect: cancelled, no reply
        if isinstance(verts, ServingError):
            return await self._serving_error(writer, verts, tier)
        await self._respond(writer, 200, {
            "verts": proto.encode_array(np.asarray(verts))})
        return True

    async def _await_future(self, fut, rd: _Pushback,
                            deadline_s: Optional[float]):
        """Await one engine future while watching the connection: a
        client disconnect cancels the future (the PR-13 path) instead
        of serving a result nobody reads. Returns (result-or-
        ServingError, client_gone)."""
        afut = asyncio.ensure_future(asyncio.wrap_future(fut))
        eof = asyncio.ensure_future(rd.read1())
        # Backstop only: the engine's own deadline sweep resolves
        # expired futures — this cap exists so a deadline-less request
        # cannot pin a drained server forever.
        cap = None if deadline_s is None else deadline_s + 60.0
        try:
            while True:
                waiters = {afut} if eof is None else {afut, eof}
                done, _pending = await asyncio.wait(
                    waiters, timeout=cap,
                    return_when=asyncio.FIRST_COMPLETED)
                if afut in done:
                    break
                if eof is not None and eof in done:
                    data = eof.result()
                    if data:
                        # A pipelined byte, not a disconnect: push it
                        # back for the next request's parser and keep
                        # waiting (one watcher byte is enough — a
                        # half-closed writer still surfaces as EOF).
                        rd.push(data)
                        eof = None
                        continue
                    fut.cancel()
                    return None, True
                if not done:            # cap elapsed: backstop expiry
                    fut.cancel()
                    return ServingError(
                        "edge wait cap elapsed before the engine "
                        "resolved this request", phase="edge",
                        kind="error"), False
        finally:
            if eof is not None:
                if not eof.done():
                    eof.cancel()
                # Await the watcher OUT of the reader: task cancel is
                # asynchronous, and the next readline() would race a
                # still-pending read1() ("another coroutine is already
                # waiting"). A byte it managed to read before the
                # cancel landed belongs to the NEXT request — push it
                # back.
                try:
                    data = await eof
                    if data:
                        rd.push(data)
                except (asyncio.CancelledError, ConnectionError,
                        Exception):  # noqa: BLE001 — EOF errors land
                    pass             # again at the next reader call
            if not afut.done():
                afut.cancel()
        try:
            return afut.result(), False
        except ServingError as e:
            return e, False
        except asyncio.CancelledError:
            return ServingError("request cancelled at the engine",
                                phase="edge", kind="error"), False

    async def _serving_error(self, writer, e: ServingError,
                             tier: int) -> bool:
        status = proto.KIND_STATUS.get(e.kind, 500)
        extra = None
        if status == 429:
            # Backpressure: the Retry-After is the controller's
            # actuated value when one is attached and has an opinion
            # (PR 19), else derived from load()'s per-tier admission
            # state (protocol.retry_after_s) — the static formula.
            try:
                load = self._engine.load()
            except Exception:  # noqa: BLE001 — the header is advisory
                load = None
            retry_s = None
            if self._retry_after_source is not None:
                try:
                    retry_s = self._retry_after_source(tier, load)
                except Exception:  # noqa: BLE001 — advisory header;
                    retry_s = None  # a sick controller must not 500 a 429
            if retry_s is None:
                retry_s = proto.retry_after_s(tier, load)
            extra = {"Retry-After": int(retry_s)}
        flight = (self._flight(f"edge_5xx_{e.kind}")
                  if status >= 500 else None)
        await self._respond(writer, status, proto.error_body(
            e.kind, str(e), phase=getattr(e, "phase", "edge"),
            flight=flight), extra_headers=extra)
        return True

    async def _h_specialize(self, req: _Request, writer) -> bool:
        try:
            body = json.loads(req.body or b"{}")
            betas = proto.decode_array(body["betas"])
        except (KeyError, ValueError, TypeError) as e:
            await self._respond(writer, 400, proto.error_body(
                "bad_request", f"malformed specialize request: {e}"))
            return True
        loop = asyncio.get_running_loop()
        try:
            # specialize() bakes on device — executor, never the loop.
            key = await loop.run_in_executor(
                None, lambda: self._engine.specialize(betas))
        except (ValueError, TypeError) as e:
            # Engine-side caller errors (wrong betas length) are 400s,
            # exactly like _h_forward's — not 500-with-flight
            # incidents.
            await self._respond(writer, 400, proto.error_body(
                "bad_request", f"malformed specialize request: {e}"))
            return True
        await self._respond(writer, 200, {"subject": key})
        return True

    # -------------------------------------------------------------- streams
    async def _h_stream(self, rd: _Pushback, writer) -> bool:
        # The upgraded connection OUTLIVES the request that opened it:
        # an idle session parked in readline must not count as an
        # in-flight request, or drain() burns its whole window waiting
        # on a client that owes nothing. Release the _handle loop's
        # count here (its finally rebalances); per-FRAME work
        # re-enters via _stream_frame, which is the drain-visible
        # unit.
        self._active_requests -= 1
        try:
            return await self._h_stream_inner(rd, writer)
        finally:
            self._active_requests += 1

    async def _h_stream_inner(self, rd: _Pushback, writer) -> bool:
        writer.write(
            b"HTTP/1.1 101 Switching Protocols\r\n"
            b"Upgrade: " + proto.STREAM_UPGRADE.encode() + b"\r\n"
            b"Connection: Upgrade\r\n\r\n")
        await writer.drain()
        loop = asyncio.get_running_loop()
        eng = self._engine
        sess = None
        disconnected = False
        try:
            while True:
                line = await rd.readline()
                if not line:
                    disconnected = True
                    break
                try:
                    msg = json.loads(line)
                    op = msg.get("op")
                except ValueError:
                    await self._send_line(writer, proto.error_body(
                        "bad_request", "stream frames must be one JSON "
                        "object per line"))
                    disconnected = True
                    break
                if op == "open":
                    if sess is not None:
                        await self._send_line(writer, proto.error_body(
                            "bad_request",
                            "stream already open on this connection"))
                        continue
                    try:
                        subject = msg.get("subject")
                        if subject is None:
                            subject = proto.decode_array(msg["betas"])
                        kw = {k: msg[k] for k in
                              ("n_steps", "data_term", "solver")
                              if k in msg}
                        if msg.get("resume_pose") is not None:
                            # PR-18 migration handoff: a proxy re-opens
                            # a drained worker's session on a sibling
                            # warm-started at the last confirmed pose
                            # (PR-12 portability — deterministic fits
                            # make the continuation bit-equal).
                            kw["resume_pose"] = proto.decode_array(
                                msg["resume_pose"])
                        sess = await loop.run_in_executor(
                            None, lambda: eng.open_stream(
                                subject,
                                frame_deadline_s=msg.get(
                                    "frame_deadline_s"),
                                idle_timeout_s=msg.get("idle_timeout_s"),
                                **kw))
                    except ServingError as e:
                        await self._send_line(writer, proto.error_body(
                            e.kind, str(e), phase="stream"))
                        continue
                    except (KeyError, ValueError, TypeError) as e:
                        await self._send_line(writer, proto.error_body(
                            "bad_request", f"malformed open: {e}"))
                        continue
                    await self._send_line(writer, {
                        "event": "opened",
                        "stream_id": sess.stream_id,
                        "subject": sess.subject,
                    })
                elif op == "frame":
                    if sess is None:
                        await self._send_line(writer, proto.error_body(
                            "bad_request", "no open stream — send "
                            '{"op": "open", ...} first'))
                        continue
                    try:
                        target = proto.decode_array(msg["target"])
                    except (KeyError, ValueError) as e:
                        await self._send_line(writer, proto.error_body(
                            "bad_request", f"malformed frame: {e}"))
                        continue
                    gone = await self._stream_frame(
                        sess, target, msg, rd, writer, loop)
                    if gone:
                        disconnected = True
                        break
                elif op == "close":
                    if sess is not None:
                        sess.close()
                    await self._send_line(writer, {
                        "event": "closed",
                        "frames": (0 if sess is None
                                   else sess.frames_submitted)})
                    break
                else:
                    await self._send_line(writer, proto.error_body(
                        "bad_request", f"unknown stream op {op!r}"))
        except (ConnectionError, asyncio.IncompleteReadError):
            disconnected = True
        finally:
            if sess is not None and disconnected:
                # The socket died with the session open: the client is
                # gone, so close the session (terminal "closed" —
                # span-once) rather than waiting for an idle sweep.
                sess.close()
        return False                    # an upgraded connection is done

    async def _stream_frame(self, sess, target, msg, rd: _Pushback,
                            writer, loop) -> bool:
        """One frame end-to-end; returns True when the client vanished
        (the in-flight frame future is cancelled — PR-13 — and the
        caller closes the session)."""
        self._active_requests += 1
        try:
            kw = ({"deadline_s": msg["deadline_s"]}
                  if "deadline_s" in msg else {})
            # submit_frame runs the frozen-shape LM fit in its calling
            # thread (streams.py) — executor, never the loop.
            fut = await loop.run_in_executor(
                None, lambda: sess.submit_frame(target, **kw))
            res, gone = await self._await_future(
                fut, rd, msg.get("deadline_s", sess.frame_deadline_s))
            if gone:
                return True
            if isinstance(res, ServingError):
                await self._send_line(writer, proto.error_body(
                    res.kind, str(res), phase="stream"))
                return False
            await self._send_line(writer, {
                "event": "frame",
                "frame": int(res.frame),
                "fit_loss": float(res.fit_loss),
                "pose": proto.encode_array(np.asarray(res.pose)),
                "verts": proto.encode_array(np.asarray(res.verts)),
            })
            return False
        finally:
            self._active_requests -= 1

    async def _send_line(self, writer, obj) -> None:
        writer.write(proto.dumps(obj) + b"\n")
        await writer.drain()
