"""Fleet front tier: a health-aware socket proxy over N edge workers.

PR 15 made ONE worker reachable over the wire; this module makes a
FLEET of them killable, drainable, and redeployable without dropping a
user (ROADMAP item 1's production shape). It is a process-level layer
over machinery that already exists — every decision maps down:

* **Routing** is health-aware least-loaded: each backend owns a
  ``runtime/health.py`` CircuitBreaker whose probe is a BOUNDED
  ``/healthz`` GET (socket liveness, not chip liveness — the breaker's
  priority-claim stand-down is therefore off: probing a loopback
  socket never contends for the device). A DOWN backend is routed
  around; its re-probe runs on a disposable thread kicked from the
  routing path via ``probe_due()`` — the serving/lanes.py pattern, so
  the accept loop never pays a probe.
* **Idempotent re-route**: a backend that fails AT CONNECT never saw
  the request — the proxy silently retries a sibling (mirrors
  EdgeClient's attempt-0 rule). Any failure AFTER the request hit the
  wire is terminal 502 ``upstream``: the worker may have admitted the
  work, and a blind resend would double-submit (protocol.py).
* **Backpressure passthrough**: a worker's 429 travels to the client
  verbatim, ``Retry-After`` included — the engine's PR-5 admission
  decision stays the engine's.
* **Specialize broadcast**: subject keys are content-addressed
  (sha256 of the betas bytes — serving/engine.py), so the SAME betas
  yield the SAME key on every worker. ``/v1/specialize`` fans out to
  all routable backends and the key is valid fleet-wide; the payload
  is remembered and replayed to late-joining backends
  (``add_backend`` — the rolling-deploy path).
* **Stream MIGRATION** (the tentpole): the proxy terminates the
  ``mano-stream/1`` upgrade itself and relays NDJSON ops to a backend
  session, remembering the original open msg and the last CONFIRMED
  pose off each frame reply (already wire-encoded — zero re-encode).
  When the backend dies mid-frame or is drained, the relay re-opens on
  a sibling with ``resume_pose=<last confirmed pose>`` — the PR-12
  warm-start handoff — and re-sends the in-flight frame. Deterministic
  pure fits make the continuation BIT-equal to an uninterrupted
  session (the config21 judge asserts it). Re-sending is safe exactly
  here: the old reply never reached the client (one reply line per op,
  strictly ordered), and the resumed fit re-derives it from the same
  confirmed state. Client-visible frame numbers stay continuous: the
  sibling's session restarts its 0-based counter, and the relay adds
  the confirmed-frame offset to every relayed reply.
* **Span accounting across processes**: a drain migration closes the
  old worker's session with a polite ``{"op": "close"}`` (its span
  closes ``closed``, exactly once, in THAT worker's tracer) before the
  sibling opens a fresh span — no span is ever double-closed or
  leaked by the handoff. A SIGKILLed worker takes its tracer with it;
  its spans are excluded from fleet accounting by construction (the
  drill documents this).

The proxy holds NO device, NO engine, and NO JAX — it is pure socket
work on one asyncio loop in a daemon thread (the EdgeServer lifecycle
shape), importable without touching the accelerator stack.
"""

from __future__ import annotations

import asyncio
import json
import threading
import time
from typing import Callable, Dict, Optional

from mano_hand_tpu.edge import protocol as proto
from mano_hand_tpu.edge.server import (
    _LINE_LIMIT, MAX_BODY_BYTES, _Pushback, _Request, read_request,
    write_response)
from mano_hand_tpu.runtime.health import DOWN, HEALTHY, CircuitBreaker


class BackendConnectError(Exception):
    """Connect (or upgrade) to a backend failed with NOTHING
    dispatched — re-routing to a sibling is idempotent."""


class BackendMidstreamError(Exception):
    """The backend failed AFTER a request hit its wire — never
    re-sent; maps to 502 ``upstream``."""


class _OpenRefused(Exception):
    """The backend answered a stream open with an error LINE (shed,
    bad request): a protocol-level refusal, not a dead socket."""

    def __init__(self, reply: dict):
        self.reply = reply
        super().__init__(str(reply.get("error")))


class Backend:
    """One ``mano serve`` worker as the proxy sees it: address +
    breaker + live-load bookkeeping (loop-thread-owned counters)."""

    def __init__(self, name: str, host: str, port: int, *,
                 probe_timeout_s: float = 2.0,
                 breaker: Optional[CircuitBreaker] = None):
        self.name = name
        self.host = host
        self.port = int(port)
        self.probe_timeout_s = float(probe_timeout_s)
        self.breaker = breaker if breaker is not None else CircuitBreaker(
            failure_threshold=3,
            probe=self._healthz_probe,
            probe_interval_s=0.25,
            probe_backoff=2.0,
            probe_interval_cap_s=8.0,
            # A loopback socket probe never contends for the chip: the
            # driver's priority claim is about DEVICE traffic.
            respect_priority_claim=False)
        self.draining = False
        self.outstanding = 0            # one-shot requests in flight
        self.streams: set = set()       # live _StreamRelay objects
        # The worker's own /healthz ``warm_streams`` fact (PR 20).
        # Tri-state: None = unknown (old worker, or no probe yet) stays
        # ELIGIBLE for stream opens — exactly the pre-PR-20 fleet;
        # False = the worker said it booted cold, so NEW streams prefer
        # a warm sibling (the cold-stream-start guard).
        self.stream_warm: Optional[bool] = None
        # Proxy-assigned registration stamp: 0 for the boot-time fleet,
        # monotone-increasing for scale-up joins (``add_backend``).
        # Only consulted as a stream-open tie-break, so the boot-time
        # fleet's routing order is byte-identical to before.
        self.boot_seq = 0

    def _healthz_probe(self) -> bool:
        """Bounded liveness GET (runs on a disposable thread, never the
        loop): any parsed /healthz answer means the process is back."""
        from mano_hand_tpu.edge.client import EdgeClient, EdgeError

        try:
            with EdgeClient(self.host, self.port,
                            timeout_s=self.probe_timeout_s) as cli:
                h = cli.healthz()
            if "warm_streams" in h:
                self.stream_warm = bool(h["warm_streams"])
            return h.get("status") == "serving"
        except (EdgeError, OSError, ValueError):
            return False

    def routable(self) -> bool:
        return not self.draining and self.breaker.state != DOWN

    def load(self) -> int:
        return self.outstanding + len(self.streams)


class EdgeProxy:
    """Socket-level load balancer + stream migrator over N workers.

    Same lifecycle contract as ``EdgeServer``: event loop in a daemon
    thread (``start()``), ``drain()`` callable from any thread,
    ``port=0`` binds ephemeral. ``drain_backend(name)`` is the rolling
    -deploy primitive: stop routing to one worker and hand its live
    streams to siblings mid-stream, bounded by a budget.
    """

    def __init__(self, backends, host: str = "127.0.0.1", port: int = 0,
                 *, drain_timeout_s: float = 10.0,
                 connect_timeout_s: float = 5.0,
                 probe_timeout_s: float = 2.0,
                 upstream_timeout_s: float = 300.0,
                 max_body_bytes: int = MAX_BODY_BYTES,
                 role: str = "active",
                 takeovers: int = 0,
                 retry_after_source: Optional[Callable] = None,
                 log: Optional[Callable[[str], None]] = None):
        self._backends: Dict[str, Backend] = {}
        self._boot_seq = 0              # bumped by add_backend only
        for i, be in enumerate(backends):
            if not isinstance(be, Backend):
                host_i, port_i = be
                be = Backend(f"w{i}", host_i, port_i,
                             probe_timeout_s=probe_timeout_s)
            self._backends[be.name] = be
        # Active/standby (PR 20): ``role`` is what this process IS
        # right now ("active" serves; "standby" is parked on the flock
        # in cli.cmd_proxy and never reaches start()). ``takeovers`` is
        # the lock file's takeover generation at activation — 0 for a
        # first-boot active, N for the Nth flock winner.
        self.role = str(role)
        self.takeovers = int(takeovers)
        self.host = host
        self.port = int(port)
        self.drain_timeout_s = float(drain_timeout_s)
        self.connect_timeout_s = float(connect_timeout_s)
        self.probe_timeout_s = float(probe_timeout_s)
        self.upstream_timeout_s = float(upstream_timeout_s)
        self.max_body_bytes = int(max_body_bytes)
        # Closed-loop control (PR 19): optional ``(tier, load) ->
        # Optional[int]`` for PROXY-originated 503s (no routable
        # backend / draining). Worker-originated 429s keep relaying
        # the worker's own Retry-After verbatim — the worker's
        # controller owns that value; this source only covers
        # responses the proxy itself synthesizes (load is None there —
        # the proxy has no engine). None -> no header, today's wire.
        self._retry_after_source = retry_after_source
        self._log = log or (lambda m: None)
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._server: Optional[asyncio.AbstractServer] = None
        self._thread: Optional[threading.Thread] = None
        self._ready = threading.Event()
        self._boot_error: Optional[BaseException] = None
        self._conn_tasks: set = set()
        self._active_requests = 0
        self._draining = False
        self._drained = False
        self._t0 = time.monotonic()
        # Replay registry: specialize bodies by subject key, so a
        # late-joining backend (rolling deploy) learns every subject.
        self._specialized: Dict[str, bytes] = {}
        # Counters (loop-thread-owned; exported via /metrics).
        self.requests_proxied = 0
        self.reroutes = 0               # idempotent connect-fail retries
        self.upstream_failures = 0      # 502s — failed after dispatch
        self.streams_opened = 0
        self.frames_relayed = 0
        self.migrations = 0             # sessions handed to a sibling
        self.migrated_frames = 0        # in-flight frames re-sent

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "EdgeProxy":
        if self._thread is not None:
            return self
        self._thread = threading.Thread(
            target=self._run, name="mano-proxy", daemon=True)
        self._thread.start()
        if not self._ready.wait(timeout=30.0):
            raise RuntimeError("proxy failed to bind within 30s")
        if self._boot_error is not None:
            raise RuntimeError(
                f"proxy failed to start: {self._boot_error}")
        return self

    def _run(self) -> None:
        loop = asyncio.new_event_loop()
        self._loop = loop
        try:
            loop.run_until_complete(self._serve_main())
        except BaseException as e:  # noqa: BLE001 — surface via start()
            self._boot_error = e
            self._ready.set()
        finally:
            try:
                loop.close()
            except Exception:  # noqa: BLE001
                pass

    async def _serve_main(self) -> None:
        self._server = await asyncio.start_server(
            self._handle, self.host, self.port, limit=_LINE_LIMIT)
        self.port = self._server.sockets[0].getsockname()[1]
        self._stop_event = asyncio.Event()
        self._ready.set()
        self._log(f"proxy listening on {self.host}:{self.port} over "
                  f"{len(self._backends)} backends")
        await self._stop_event.wait()

    def drain(self, timeout_s: Optional[float] = None) -> dict:
        """Stop the PROXY itself (refuse new connections, resolve
        in-flight one-shots, cancel relays — each relay's cleanup
        closes its backend socket, which is the worker's documented
        disconnect path). Idempotent, callable from any thread."""
        if timeout_s is None:
            timeout_s = self.drain_timeout_s
        if self._loop is None or self._drained:
            return {"drained": self._drained, "already": True}
        t0 = time.monotonic()
        fut = asyncio.run_coroutine_threadsafe(
            self._drain_async(float(timeout_s)), self._loop)
        try:
            report = fut.result(timeout=timeout_s + 30.0)
        except Exception as e:  # noqa: BLE001 — report, never hang
            report = {"drained": False,
                      "error": f"{type(e).__name__}: {e}"}
        self._drained = True
        if self._thread is not None:
            self._thread.join(timeout=10.0)
        report["wall_s"] = round(time.monotonic() - t0, 4)
        return report

    async def _drain_async(self, timeout_s: float) -> dict:
        deadline = time.monotonic() + timeout_s
        self._draining = True
        srv = self._server
        if srv is not None:
            srv.close()
            await srv.wait_closed()
        while self._active_requests > 0 and time.monotonic() < deadline:
            await asyncio.sleep(0.005)
        inflight_resolved = self._active_requests == 0
        for t in list(self._conn_tasks):
            if not t.done():
                t.cancel()
        if self._conn_tasks:
            await asyncio.wait(list(self._conn_tasks), timeout=1.0)
        self._stop_event.set()
        return {
            "drained": True,
            "inflight_resolved": inflight_resolved,
            "requests_proxied": self.requests_proxied,
            "within_timeout": time.monotonic() <= deadline,
        }

    # ------------------------------------------------------- fleet control
    def backends(self) -> Dict[str, Backend]:
        return dict(self._backends)

    def add_backend(self, be: Backend,
                    replay_timeout_s: float = 10.0) -> None:
        """Register a new worker (rolling deploy's scale-up half) and
        replay every known specialize so subject-keyed traffic can
        land on it immediately. Callable from any thread; the replay
        is bounded and best-effort (a failure only degrades the
        breaker — subject traffic re-routes around it)."""
        from mano_hand_tpu.edge.client import EdgeClient, EdgeError

        self._boot_seq += 1
        be.boot_seq = self._boot_seq
        # Cold-stream-start guard (PR 20): learn the worker's
        # ``warm_streams`` fact BEFORE it becomes routable, so a cold
        # scale-up worker cannot win a stream open purely by being the
        # idlest process in the fleet. Best-effort and bounded: an
        # unreadable fact leaves the tri-state at None (eligible).
        try:
            with EdgeClient(be.host, be.port,
                            timeout_s=be.probe_timeout_s) as cli:
                h = cli.healthz()
            if "warm_streams" in h:
                be.stream_warm = bool(h["warm_streams"])
        except (EdgeError, OSError, ValueError):
            pass
        self._backends[be.name] = be
        deadline = time.monotonic() + float(replay_timeout_s)
        for body in list(self._specialized.values()):
            left = deadline - time.monotonic()
            if left <= 0:
                break
            try:
                with EdgeClient(be.host, be.port,
                                timeout_s=min(left, 10.0)) as cli:
                    cli._checked("POST", "/v1/specialize",
                                 json.loads(body))
            except (EdgeError, OSError, ValueError):
                be.breaker.record_failure()
                break

    def remove_backend(self, name: str) -> None:
        self._backends.pop(name, None)

    def resync_backends(self, timeout_s: float = 10.0) -> dict:
        """Rebuild per-backend routing state from the workers' own
        ``/healthz`` — the standby-takeover path (PR 20): a freshly
        active proxy must not inherit an empty breaker ledger that
        routes the first post-takeover frames at a corpse. Bounded
        CONCURRENT sweep on disposable threads (no event loop needed —
        callable BEFORE ``start()``, which is exactly when cmd_proxy
        runs it). A live worker is recorded healthy (plus its
        ``warm_streams`` fact); a dead one is driven to DOWN through
        the breaker's own public failure path, so the breaker's
        re-probe ladder owns its recovery exactly as if the failures
        had been observed in traffic. Returns ``{name: ok}``."""
        results: Dict[str, bool] = {}

        def sweep(be: Backend) -> None:
            from mano_hand_tpu.edge.client import EdgeClient, EdgeError

            try:
                with EdgeClient(be.host, be.port,
                                timeout_s=min(float(timeout_s),
                                              be.probe_timeout_s)) as c:
                    h = c.healthz()
                ok = h.get("status") == "serving"
            except (EdgeError, OSError, ValueError):
                ok, h = False, {}
            if ok:
                if "warm_streams" in h:
                    be.stream_warm = bool(h["warm_streams"])
                be.breaker.record_success()
            else:
                # Classified and bounded: feed consecutive failures
                # until the threshold trips — never a raw state poke,
                # so the transition callback/ledger stay truthful.
                for _ in range(64):
                    if be.breaker.record_failure() == DOWN:
                        break
            results[be.name] = ok

        threads = [threading.Thread(target=sweep, args=(be,),
                                    name=f"resync-{be.name}",
                                    daemon=True)
                   for be in list(self._backends.values())]
        for t in threads:
            t.start()
        deadline = time.monotonic() + float(timeout_s)
        for t in threads:
            t.join(timeout=max(0.0, deadline - time.monotonic()))
        return results

    def drain_backend(self, name: str,
                      timeout_s: float = 10.0) -> dict:
        """The rolling-deploy primitive: stop routing to ``name`` and
        migrate its live streams to siblings (each relay hands its
        session over with a polite close + ``resume_pose`` re-open —
        no frame is dropped). Blocks (bounded) until the worker holds
        no proxied work; the WORKER process is then safe to SIGTERM.
        Callable from any thread."""
        if self._loop is None:
            raise RuntimeError("proxy is not running")
        fut = asyncio.run_coroutine_threadsafe(
            self._drain_backend_async(name, float(timeout_s)),
            self._loop)
        return fut.result(timeout=timeout_s + 30.0)

    async def _drain_backend_async(self, name: str,
                                   timeout_s: float) -> dict:
        be = self._backends.get(name)
        if be is None:
            return {"backend": name, "error": "unknown backend"}
        t0 = time.monotonic()
        deadline = t0 + timeout_s
        be.draining = True              # routing stops NOW
        migrating = len(be.streams)
        for relay in list(be.streams):
            relay.migrate_evt.set()     # proactive: idle relays move too
        while ((be.streams or be.outstanding)
               and time.monotonic() < deadline):
            await asyncio.sleep(0.005)
        return {
            "backend": name,
            "streams_migrated": migrating,
            "clean": not be.streams and be.outstanding == 0,
            "residual_streams": len(be.streams),
            "residual_outstanding": be.outstanding,
            "wall_s": round(time.monotonic() - t0, 4),
        }

    # -------------------------------------------------------------- routing
    def _pick(self, exclude=()) -> Optional[Backend]:
        """Healthy-first, least-loaded, name as the deterministic
        tie-break; kicks due re-probes onto disposable threads."""
        cands = []
        for be in self._backends.values():
            if be.breaker.probe_due():
                threading.Thread(target=be.breaker.allow_primary,
                                 name=f"probe-{be.name}",
                                 daemon=True).start()
            if be.routable() and be.name not in exclude:
                cands.append(be)
        if not cands:
            return None
        cands.sort(key=lambda b: (
            0 if b.breaker.state == HEALTHY else 1, b.load(), b.name))
        return cands[0]

    def _pick_stream(self, exclude=()) -> Optional[Backend]:
        """Stream-open placement (PR 20): like ``_pick`` but a worker
        that told us it booted COLD (``stream_warm is False``) must not
        win a new open while a warm (or unknown — pre-fact) sibling is
        routable, or the client's first frames pay that worker's jit
        wall. Unknown (None) stays eligible — exactly the pre-PR-20
        fleet. Among survivors the sort adds ``-boot_seq`` before the
        name tie-break: the boot-time fleet all carries seq 0 (order
        unchanged), and a WARM scale-up join is preferred at equal
        load — new capacity takes new sessions. Falls back to the
        plain pick when only cold workers remain: availability beats
        warmth."""
        cands = []
        for be in self._backends.values():
            if be.breaker.probe_due():
                threading.Thread(target=be.breaker.allow_primary,
                                 name=f"probe-{be.name}",
                                 daemon=True).start()
            if (be.routable() and be.name not in exclude
                    and be.stream_warm is not False):
                cands.append(be)
        if not cands:
            return self._pick(exclude)
        cands.sort(key=lambda b: (
            0 if b.breaker.state == HEALTHY else 1, b.load(),
            -b.boot_seq, b.name))
        return cands[0]

    async def _connect(self, be: Backend):
        try:
            return await asyncio.wait_for(
                asyncio.open_connection(be.host, be.port,
                                        limit=_LINE_LIMIT),
                self.connect_timeout_s)
        except (OSError, asyncio.TimeoutError) as e:
            raise BackendConnectError(
                f"{be.name} unreachable: {type(e).__name__}: {e}") from e

    # ----------------------------------------------------------- connection
    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        task = asyncio.current_task()
        self._conn_tasks.add(task)
        rd = _Pushback(reader)
        try:
            while True:
                req = await read_request(
                    rd, writer, max_body_bytes=self.max_body_bytes,
                    draining=self._draining)
                if req is None:
                    break
                self._active_requests += 1
                try:
                    keep = await self._dispatch(req, rd, writer)
                finally:
                    self._active_requests -= 1
                    self.requests_proxied += 1
                if not keep or self._draining:
                    break
        except (asyncio.CancelledError, ConnectionError,
                asyncio.IncompleteReadError):
            pass
        except Exception as e:  # noqa: BLE001 — one bad conn != the proxy
            self._log(f"proxy connection error: {type(e).__name__}: {e}")
        finally:
            self._conn_tasks.discard(task)
            try:
                writer.close()
            except Exception:  # noqa: BLE001
                pass

    def _unavailable_headers(self, req: _Request) -> Optional[dict]:
        """Retry-After for a PROXY-originated 503, from the attached
        controller source (PR 19). The request's priority header is
        the tier; the proxy has no engine, so load is None. Any
        source failure or None opinion -> no header (today's wire)."""
        if self._retry_after_source is None:
            return None
        try:
            tier = int(req.headers.get(proto.PRIORITY_HEADER, 0))
        except (TypeError, ValueError):
            tier = 0
        try:
            retry_s = self._retry_after_source(tier, None)
        except Exception:  # noqa: BLE001 — advisory header only
            return None
        return None if retry_s is None else {
            "Retry-After": int(retry_s)}

    async def _dispatch(self, req: _Request, rd: _Pushback,
                        writer) -> bool:
        if self._draining:
            await write_response(writer, 503, proto.error_body(
                "shutdown", "proxy is draining; connection closing"),
                extra_headers=self._unavailable_headers(req),
                close=True)
            return False
        route = (req.method, req.path.split("?", 1)[0])
        try:
            if route == ("GET", "/healthz"):
                return await self._h_healthz(writer)
            if route == ("GET", "/metrics"):
                return await self._h_metrics(writer)
            if route == ("POST", "/v1/specialize"):
                return await self._h_specialize(req, writer)
            if route[1] == "/v1/stream":
                if (req.headers.get("upgrade") or "").lower() \
                        != proto.STREAM_UPGRADE:
                    await write_response(
                        writer, 400, proto.error_body(
                            "bad_request",
                            f"/v1/stream requires 'Upgrade: "
                            f"{proto.STREAM_UPGRADE}'"))
                    return True
                relay = _StreamRelay(self, rd, writer)
                return await relay.run()
            if route == ("POST", "/v1/forward"):
                return await self._h_relay(req, writer)
            await write_response(writer, 404, proto.error_body(
                "bad_request",
                f"no proxy route for {req.method} {req.path}"))
            return True
        except (ConnectionError, asyncio.IncompleteReadError):
            raise
        except asyncio.CancelledError:
            raise
        except Exception as e:  # noqa: BLE001 — 500, not a crash
            await write_response(writer, 500, proto.error_body(
                "error", f"proxy: {type(e).__name__}: {e}",
                phase="proxy"))
            return True

    # ----------------------------------------------------- one-shot relays
    def _request_bytes(self, req: _Request, be: Backend) -> bytes:
        head = [f"{req.method} {req.path} HTTP/1.1",
                f"Host: {be.host}:{be.port}",
                "Connection: close",
                f"Content-Length: {len(req.body)}"]
        for h in ("content-type", proto.PRIORITY_HEADER,
                  proto.DEADLINE_HEADER):
            v = req.headers.get(h)
            if v is not None:
                head.append(f"{h}: {v}")
        return ("\r\n".join(head) + "\r\n\r\n").encode("latin-1") \
            + req.body

    async def _backend_roundtrip(self, be: Backend, req: _Request):
        """One request against one backend over a fresh connection;
        returns (status, lower-cased headers, body bytes). Raises
        ``BackendConnectError`` before dispatch, ``Midstream`` after.
        """
        b_rd, b_w = await self._connect(be)
        try:
            try:
                b_w.write(self._request_bytes(req, be))
                await b_w.drain()
                return await asyncio.wait_for(
                    self._read_response(b_rd),
                    self.upstream_timeout_s)
            except (OSError, ConnectionError, asyncio.TimeoutError,
                    asyncio.IncompleteReadError, ValueError) as e:
                # Conservative: once the connect succeeded, any part
                # of the request may have reached the worker — a
                # fully-received body WILL be dispatched even if our
                # read side broke, so this is never re-routed.
                raise BackendMidstreamError(
                    f"{be.name} failed mid-response: "
                    f"{type(e).__name__}: {e}") from e
        finally:
            try:
                b_w.close()
            except Exception:  # noqa: BLE001
                pass

    @staticmethod
    async def _read_response(b_rd: asyncio.StreamReader):
        line = await b_rd.readline()
        if not line:
            raise ConnectionError("backend closed before the status line")
        parts = line.decode("latin-1").strip().split(" ", 2)
        status = int(parts[1])
        headers = {}
        while True:
            h = await b_rd.readline()
            if h in (b"\r\n", b"\n"):
                break
            if not h:
                raise ConnectionError("backend closed mid-headers")
            name, _, value = h.decode("latin-1").partition(":")
            headers[name.strip().lower()] = value.strip()
        n = int(headers.get("content-length", 0))
        body = await b_rd.readexactly(n) if n else b""
        return status, headers, body

    async def _h_relay(self, req: _Request, writer) -> bool:
        tried = set()
        while True:
            be = self._pick(exclude=tried)
            if be is None:
                await write_response(writer, 503, proto.error_body(
                    "shutdown", "no routable backend in the fleet",
                    phase="proxy"),
                    extra_headers=self._unavailable_headers(req))
                return True
            tried.add(be.name)
            be.outstanding += 1
            try:
                status, hdrs, payload = await self._backend_roundtrip(
                    be, req)
            except BackendConnectError:
                be.breaker.record_failure()
                self.reroutes += 1
                continue                # idempotent: never dispatched
            except BackendMidstreamError as e:
                be.breaker.record_failure()
                self.upstream_failures += 1
                await write_response(writer, 502, proto.error_body(
                    "upstream", str(e), phase="proxy"))
                return True
            finally:
                be.outstanding -= 1
            be.breaker.record_success()
            extra = {}
            ra = hdrs.get("retry-after")
            if ra is not None:          # PR-5 backpressure, verbatim
                extra["Retry-After"] = ra
            await write_response(
                writer, status, payload,
                content_type=hdrs.get("content-type",
                                      "application/json"),
                extra_headers=extra or None, close=self._draining)
            return True

    async def _h_specialize(self, req: _Request, writer) -> bool:
        """Broadcast: content-addressed keys agree across workers, so
        one 200 makes the key valid fleet-wide; failures only degrade
        the failing backend's breaker."""
        async def one(be: Backend):
            be.outstanding += 1
            try:
                return be, await self._backend_roundtrip(be, req)
            except (BackendConnectError, BackendMidstreamError) as e:
                be.breaker.record_failure()
                return be, e
            finally:
                be.outstanding -= 1

        targets = [be for be in self._backends.values()
                   if be.routable()]
        if not targets:
            await write_response(writer, 503, proto.error_body(
                "shutdown", "no routable backend in the fleet",
                phase="proxy"),
                extra_headers=self._unavailable_headers(req))
            return True
        results = await asyncio.gather(*(one(be) for be in targets))
        winner = None
        for be, res in results:
            if isinstance(res, tuple):
                be.breaker.record_success()
                status, hdrs, payload = res
                if status == 200 and winner is None:
                    winner = (status, hdrs, payload)
        if winner is None:
            # Every backend refused or failed: relay the first
            # structured answer if any backend produced one.
            for _be, res in results:
                if isinstance(res, tuple):
                    status, hdrs, payload = res
                    await write_response(
                        writer, status, payload,
                        content_type=hdrs.get("content-type",
                                              "application/json"))
                    return True
            self.upstream_failures += 1
            await write_response(writer, 502, proto.error_body(
                "upstream", "specialize failed on every backend",
                phase="proxy"))
            return True
        status, hdrs, payload = winner
        try:
            key = json.loads(payload)["subject"]
            self._specialized[key] = bytes(req.body)
        except (ValueError, KeyError, TypeError):
            pass
        await write_response(writer, status, payload,
                             content_type=hdrs.get(
                                 "content-type", "application/json"))
        return True

    # -------------------------------------------------------- health fanout
    async def _h_healthz(self, writer) -> bool:
        """Bounded CONCURRENT fan-out: one wedged worker costs its own
        timeout, not the scrape (the `mano status --server` contract).
        """
        async def probe_one(be: Backend):
            req = _Request("GET", "/healthz", {}, b"")
            try:
                _status, _hdrs, payload = await asyncio.wait_for(
                    self._backend_roundtrip(be, req),
                    self.probe_timeout_s)
                return be, json.loads(payload)
            except Exception as e:  # noqa: BLE001 — degrade per-worker
                return be, {"ok": False,
                            "error": f"{type(e).__name__}: {e}"}

        results = await asyncio.gather(
            *(probe_one(be) for be in list(self._backends.values())))
        backends = {}
        for be, h in results:
            if "warm_streams" in h:     # refresh the PR-20 warm fact
                be.stream_warm = bool(h["warm_streams"])
            backends[be.name] = {
                "ok": bool(h.get("ok", False)),
                "status": h.get("status"),
                "degraded": h.get("degraded"),
                "error": h.get("error"),
                "breaker": be.breaker.state,
                "draining_via_proxy": be.draining,
                "outstanding": be.outstanding,
                "streams": len(be.streams),
                "stream_warm": be.stream_warm,
            }
        routable = sum(1 for be, _h in results if be.routable())
        ok = not self._draining and routable > 0
        body = {
            "ok": ok,
            "role": "proxy",
            "proxy_role": self.role,
            "takeovers": self.takeovers,
            "status": "draining" if self._draining else "proxying",
            "degraded": 0 < routable < len(backends),
            "uptime_s": round(time.monotonic() - self._t0, 3),
            "backends": backends,
            "streams": {"active": sum(
                len(be.streams) for be in self._backends.values())},
            "counters": self._counter_dict(),
        }
        await write_response(writer, 200 if ok else 503, body)
        return True

    def _counter_dict(self) -> dict:
        return {
            "requests_proxied": self.requests_proxied,
            "reroutes": self.reroutes,
            "upstream_failures": self.upstream_failures,
            "streams_opened": self.streams_opened,
            "frames_relayed": self.frames_relayed,
            "migrations": self.migrations,
            "migrated_frames": self.migrated_frames,
        }

    async def _h_metrics(self, writer) -> bool:
        """The proxy's OWN counters in Prometheus text form (workers
        keep serving their full PR-9 registries on their own ports)."""
        lines = []
        for k, v in self._counter_dict().items():
            lines.append(f"# TYPE mano_proxy_{k} counter")
            lines.append(f"mano_proxy_{k} {v}")
        lines.append("# TYPE mano_proxy_takeovers counter")
        lines.append(f"mano_proxy_takeovers {self.takeovers}")
        lines.append("# TYPE mano_proxy_active gauge")
        lines.append(
            f"mano_proxy_active {1 if self.role == 'active' else 0}")
        for be in self._backends.values():
            lab = f'{{backend="{be.name}"}}'
            lines.append(
                f"mano_proxy_backend_streams{lab} {len(be.streams)}")
            lines.append(
                f"mano_proxy_backend_routable{lab} "
                f"{1 if be.routable() else 0}")
            lines.append(
                f"mano_proxy_backend_stream_warm{lab} "
                f"{-1 if be.stream_warm is None else int(be.stream_warm)}")
        await write_response(
            writer, 200, ("\n".join(lines) + "\n").encode("utf-8"),
            content_type="text/plain; version=0.0.4")
        return True


class _StreamRelay:
    """One client stream session proxied onto (a succession of)
    backend sessions.

    The relay answers the 101 itself, then speaks strict one-line-in /
    one-line-out NDJSON both ways. State for migration: the ORIGINAL
    open msg (re-sent verbatim on handoff — betas travel with it, and
    subject keys are fleet-valid via the specialize broadcast), the
    last CONFIRMED pose (taken off each frame reply, still in wire
    encoding), and the confirmed-frame count (the numbering offset a
    sibling's fresh 0-based counter needs).
    """

    def __init__(self, proxy: EdgeProxy, rd: _Pushback, writer):
        self.proxy = proxy
        self.rd = rd
        self.writer = writer
        self.backend: Optional[Backend] = None
        self.b_rd: Optional[asyncio.StreamReader] = None
        self.b_w: Optional[asyncio.StreamWriter] = None
        self.open_msg: Optional[dict] = None
        self.last_pose: Optional[dict] = None   # wire-encoded [J,3]
        self.frames_confirmed = 0
        self.offset = 0
        self.migrate_evt = asyncio.Event()

    # ------------------------------------------------------------ plumbing
    async def _send_client(self, obj: dict) -> None:
        self.writer.write(proto.dumps(obj) + b"\n")
        await self.writer.drain()

    def _detach(self) -> None:
        if self.backend is not None:
            self.backend.streams.discard(self)
        if self.b_w is not None:
            try:
                self.b_w.close()
            except Exception:  # noqa: BLE001
                pass
        self.backend = self.b_rd = self.b_w = None

    async def _open_on(self, be: Backend, *, resume: bool):
        """Upgrade + open one backend session; returns the open reply.
        Raises ``BackendConnectError`` when nothing client-visible was
        dispatched (connect refused, upgrade refused, socket died
        before the reply — the dead worker's half-open session closes
        itself on our socket's death, span-once), ``_OpenRefused`` on
        a structured error line."""
        b_rd, b_w = await self.proxy._connect(be)
        try:
            b_w.write(
                (f"POST /v1/stream HTTP/1.1\r\n"
                 f"Host: {be.host}:{be.port}\r\n"
                 f"Upgrade: {proto.STREAM_UPGRADE}\r\n"
                 f"Connection: Upgrade\r\n"
                 f"Content-Length: 0\r\n\r\n").encode("latin-1"))
            await b_w.drain()
            status = await b_rd.readline()
            if not status.startswith(b"HTTP/1.1 101"):
                raise BackendConnectError(
                    f"{be.name} refused the stream upgrade: "
                    f"{status!r}")
            while True:                 # drain the 101 headers
                h = await b_rd.readline()
                if h in (b"\r\n", b"\n", b""):
                    break
            msg = dict(self.open_msg)
            if resume and self.last_pose is not None:
                msg["resume_pose"] = self.last_pose
            b_w.write(proto.dumps(msg) + b"\n")
            await b_w.drain()
            raw = await asyncio.wait_for(b_rd.readline(),
                                         self.proxy.upstream_timeout_s)
            if not raw:
                raise BackendConnectError(
                    f"{be.name} closed during the stream open")
            reply = json.loads(raw)
            if "error" in reply:
                raise _OpenRefused(reply)
            return b_rd, b_w, reply
        except (OSError, ConnectionError, asyncio.TimeoutError,
                asyncio.IncompleteReadError, ValueError) as e:
            try:
                b_w.close()
            except Exception:  # noqa: BLE001
                pass
            raise BackendConnectError(
                f"{be.name} died during the stream open: "
                f"{type(e).__name__}: {e}") from e
        except BaseException:
            try:
                b_w.close()
            except Exception:  # noqa: BLE001
                pass
            raise

    # ------------------------------------------------------------ handlers
    async def _handle_open(self, msg: dict) -> None:
        if self.backend is not None:
            await self._send_client(proto.error_body(
                "bad_request",
                "stream already open on this connection"))
            return
        self.open_msg = msg
        tried = set()
        while True:
            be = self.proxy._pick_stream(exclude=tried)
            if be is None:
                self.open_msg = None
                await self._send_client(proto.error_body(
                    "shutdown", "no routable backend in the fleet",
                    phase="proxy"))
                return
            tried.add(be.name)
            try:
                b_rd, b_w, reply = await self._open_on(be, resume=False)
            except BackendConnectError:
                be.breaker.record_failure()
                self.proxy.reroutes += 1
                continue
            except _OpenRefused as e:
                # A structured refusal (shed / bad open): the client's
                # problem, relayed verbatim; the connection stays
                # usable for a retry (the worker's own semantics).
                self.open_msg = None
                await self._send_client(e.reply)
                return
            break
        self.backend = be
        self.b_rd, self.b_w = b_rd, b_w
        be.streams.add(self)
        be.breaker.record_success()
        self.proxy.streams_opened += 1
        await self._send_client(reply)

    async def _migrate(self, *, polite: bool) -> bool:
        """Hand this session to a sibling, warm-started at the last
        confirmed pose. ``polite`` (the drain path) closes the old
        session with a real ``{"op": "close"}`` first so its span
        closes exactly once in the old worker's tracer; the failover
        path (backend already dead) skips the courtesy."""
        old = self.backend
        old_rd, old_w = self.b_rd, self.b_w
        if not polite:
            self._detach()
        elif old is not None and old_w is not None:
            old.streams.discard(self)
            try:
                old_w.write(proto.dumps({"op": "close"}) + b"\n")
                await old_w.drain()
                await asyncio.wait_for(old_rd.readline(), 5.0)
            except (OSError, ConnectionError, asyncio.TimeoutError,
                    asyncio.IncompleteReadError):
                pass                    # it died mid-drain: span closes
            finally:                    # via its disconnect path
                try:
                    old_w.close()
                except Exception:  # noqa: BLE001
                    pass
            self.backend = self.b_rd = self.b_w = None
        tried = {old.name} if old is not None else set()
        while True:
            be = self.proxy._pick_stream(exclude=tried)
            if be is None:
                return False
            tried.add(be.name)
            try:
                b_rd, b_w, _reply = await self._open_on(be, resume=True)
            except BackendConnectError:
                be.breaker.record_failure()
                continue
            except _OpenRefused:
                continue                # shed here: try the next sibling
            break
        self.backend = be
        self.b_rd, self.b_w = b_rd, b_w
        # The sibling's session numbers frames from 0 again; every
        # relayed reply gets the confirmed-count offset added so the
        # client sees one continuous stream.
        self.offset = self.frames_confirmed
        be.streams.add(self)
        be.breaker.record_success()
        self.proxy.migrations += 1
        return True

    async def _handle_frame(self, msg: dict) -> None:
        if self.backend is None:
            await self._send_client(proto.error_body(
                "bad_request", "no open stream — send "
                '{"op": "open", ...} first'))
            return
        if self.migrate_evt.is_set():   # drain landed between frames
            self.migrate_evt.clear()
            if not await self._migrate(polite=True):
                await self._send_client(proto.error_body(
                    "upstream", "stream lost: no sibling could adopt "
                    "the session", phase="proxy"))
                return
        line = proto.dumps(msg) + b"\n"
        resent = False
        while True:
            try:
                self.b_w.write(line)
                await self.b_w.drain()
                raw = await asyncio.wait_for(
                    self.b_rd.readline(),
                    self.proxy.upstream_timeout_s)
                if not raw:
                    raise ConnectionError("backend closed mid-frame")
                reply = json.loads(raw)
            except (OSError, ConnectionError, asyncio.TimeoutError,
                    asyncio.IncompleteReadError, ValueError) as e:
                # The migration race: this frame was IN FLIGHT when the
                # backend died. Its reply never reached the client (one
                # line per op, ordered), so re-sending on a sibling
                # warm-started from the last CONFIRMED pose re-derives
                # the SAME result (deterministic fits) — not a double
                # submit: the dead worker's partial work died with it.
                if self.backend is not None:
                    self.backend.breaker.record_failure()
                if not await self._migrate(polite=False):
                    self.proxy.upstream_failures += 1
                    await self._send_client(proto.error_body(
                        "upstream",
                        f"backend lost mid-frame and no sibling "
                        f"could adopt the session: {e}",
                        phase="proxy"))
                    return
                resent = True
                continue
            break
        if resent:
            self.proxy.migrated_frames += 1
        if reply.get("event") == "frame":
            self.last_pose = reply.get("pose")
            reply["frame"] = int(reply.get("frame", 0)) + self.offset
            self.frames_confirmed = reply["frame"] + 1
            self.proxy.frames_relayed += 1
        await self._send_client(reply)

    async def _handle_close(self) -> None:
        if self.backend is None:
            await self._send_client({"event": "closed", "frames": 0})
            return
        try:
            self.b_w.write(proto.dumps({"op": "close"}) + b"\n")
            await self.b_w.drain()
            raw = await asyncio.wait_for(self.b_rd.readline(), 10.0)
            reply = json.loads(raw) if raw else {"event": "closed"}
        except (OSError, ConnectionError, asyncio.TimeoutError,
                asyncio.IncompleteReadError, ValueError):
            # The backend died with the close in flight: its session
            # closes via the disconnect path (span-once); the client
            # still deserves a terminal.
            reply = {"event": "closed"}
        reply["frames"] = int(reply.get("frames", 0)) + self.offset
        await self._send_client(reply)

    # ---------------------------------------------------------------- loop
    async def run(self) -> bool:
        self.writer.write(
            b"HTTP/1.1 101 Switching Protocols\r\n"
            b"Upgrade: " + proto.STREAM_UPGRADE.encode() + b"\r\n"
            b"Connection: Upgrade\r\n\r\n")
        await self.writer.drain()
        # Like EdgeServer._h_stream: an idle parked session must not
        # count as an in-flight request against the proxy drain.
        self.proxy._active_requests -= 1
        line_task = None
        try:
            while True:
                if line_task is None:
                    line_task = asyncio.ensure_future(
                        self.rd.readline())
                mig_task = asyncio.ensure_future(
                    self.migrate_evt.wait())
                done, _ = await asyncio.wait(
                    {line_task, mig_task},
                    return_when=asyncio.FIRST_COMPLETED)
                if line_task not in done:
                    # A drain fired while the client is idle: migrate
                    # NOW (the drain budget cannot wait on a client
                    # that owes nothing), keep the parked read.
                    self.migrate_evt.clear()
                    if self.backend is not None:
                        if not await self._migrate(polite=True):
                            await self._send_client(proto.error_body(
                                "upstream",
                                "stream lost during a backend drain: "
                                "no sibling could adopt the session",
                                phase="proxy"))
                            break
                    continue
                if not mig_task.done():
                    mig_task.cancel()
                line = line_task.result()
                line_task = None
                if not line:
                    break               # client gone: cleanup in finally
                try:
                    msg = json.loads(line)
                    op = msg.get("op")
                except ValueError:
                    await self._send_client(proto.error_body(
                        "bad_request", "stream frames must be one "
                        "JSON object per line"))
                    break
                self.proxy._active_requests += 1
                try:
                    if op == "open":
                        await self._handle_open(msg)
                    elif op == "frame":
                        await self._handle_frame(msg)
                    elif op == "close":
                        await self._handle_close()
                        break
                    else:
                        await self._send_client(proto.error_body(
                            "bad_request",
                            f"unknown stream op {op!r}"))
                finally:
                    self.proxy._active_requests -= 1
        except (ConnectionError, asyncio.IncompleteReadError):
            pass
        finally:
            if line_task is not None and not line_task.done():
                line_task.cancel()
            # A vanished client (or any exit) hard-closes the backend
            # socket: the worker's disconnect path cancels in-flight
            # work and closes the session span — exactly once.
            self._detach()
            self.proxy._active_requests += 1
        return False                    # an upgraded connection is done
