"""The network edge (PR 15): ``ServingEngine`` behind a wire protocol.

* ``protocol`` — the shared byte-level conventions (lossless array
  encoding, QoS headers, kind -> status mapping, Retry-After policy,
  the stream-upgrade NDJSON vocabulary);
* ``server.EdgeServer`` — the thin asyncio HTTP front-end process
  (`mano serve` is its CLI);
* ``client.EdgeClient`` / ``client.EdgeStreamClient`` — the bounded
  stdlib client the config18 drill, tests, and `mano status --server`
  share;
* ``proxy.EdgeProxy`` — the fleet front tier (PR 18): health-aware
  routing over N workers with live stream migration;
* ``fleet.Fleet`` / ``fleet.WorkerProc`` — kill -9-capable worker
  process supervision (the chaos drill's substrate);
* ``fleet.FleetSupervisor`` / ``fleet.ProxyPair`` — the self-healing
  tier (PR 20): auto-restart of dead workers inside a budget, and the
  active/standby proxy pair behind flock takeover;
* ``client.ResilientStream`` — client-side reconnect-and-resume, so a
  SIGKILLed proxy loses no stream.
"""

from mano_hand_tpu.edge.client import (  # noqa: F401
    EdgeClient,
    EdgeError,
    EdgeStreamClient,
    FrameReply,
    ResilientStream,
)
from mano_hand_tpu.edge.fleet import (  # noqa: F401
    Fleet,
    FleetSupervisor,
    ProxyPair,
    ProxyProc,
    ProxySpec,
    WorkerProc,
    WorkerSpec,
)
from mano_hand_tpu.edge.proxy import Backend, EdgeProxy  # noqa: F401
from mano_hand_tpu.edge.server import EdgeServer  # noqa: F401

__all__ = [
    "Backend",
    "EdgeClient",
    "EdgeError",
    "EdgeProxy",
    "EdgeServer",
    "EdgeStreamClient",
    "Fleet",
    "FleetSupervisor",
    "FrameReply",
    "ProxyPair",
    "ProxyProc",
    "ProxySpec",
    "ResilientStream",
    "WorkerProc",
    "WorkerSpec",
]
