"""The network edge (PR 15): ``ServingEngine`` behind a wire protocol.

* ``protocol`` — the shared byte-level conventions (lossless array
  encoding, QoS headers, kind -> status mapping, Retry-After policy,
  the stream-upgrade NDJSON vocabulary);
* ``server.EdgeServer`` — the thin asyncio HTTP front-end process
  (`mano serve` is its CLI);
* ``client.EdgeClient`` / ``client.EdgeStreamClient`` — the bounded
  stdlib client the config18 drill, tests, and `mano status --server`
  share.
"""

from mano_hand_tpu.edge.client import (  # noqa: F401
    EdgeClient,
    EdgeError,
    EdgeStreamClient,
    FrameReply,
)
from mano_hand_tpu.edge.server import EdgeServer  # noqa: F401

__all__ = [
    "EdgeClient",
    "EdgeError",
    "EdgeServer",
    "EdgeStreamClient",
    "FrameReply",
]
