"""Loopback/test client for the edge wire protocol (stdlib-only).

The config18 drill, the edge tests, and `mano status --server` all
speak to a live edge through THIS module, so the bytes the server is
judged against are produced by one shared implementation (the
protocol.py single-owner rule). It is deliberately synchronous —
drill workers are threads with one persistent connection each, the
shape real load-generator fleets take.

Every call is BOUNDED: the socket timeout covers connect and each
read, so a wedged server degrades to a structured ``EdgeError``
(never a hang — the `mano status` probe contract).
"""

from __future__ import annotations

import http.client
import json
import socket
from typing import NamedTuple, Optional

import numpy as np

from mano_hand_tpu.edge import protocol as proto


class EdgeError(RuntimeError):
    """A structured edge failure: HTTP status + the server's
    kind/phase/message error body (+ Retry-After when the server sent
    backpressure)."""

    def __init__(self, status: int, body: Optional[dict] = None,
                 message: str = ""):
        err = (body or {}).get("error") or {}
        self.status = int(status)
        self.kind = err.get("kind", "error")
        self.phase = err.get("phase", "edge")
        self.flight = (body or {}).get("flight")
        self.retry_after_s: Optional[int] = None
        super().__init__(
            message or f"edge {status}: [{self.kind}] "
                       f"{err.get('message', '')}")


class FrameReply(NamedTuple):
    """One wire stream frame (mirrors serving.streams.FrameResult)."""

    pose: np.ndarray
    verts: np.ndarray
    fit_loss: float
    frame: int


class EdgeClient:
    """One persistent HTTP/1.1 connection to an edge worker.

    Thread-compatible, not thread-safe: one client per worker thread
    (the persistent-connection-per-worker shape). ``timeout_s`` bounds
    connect and every read.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 8080,
                 *, timeout_s: float = 30.0):
        self.host = host
        self.port = int(port)
        self.timeout_s = float(timeout_s)
        self._conn: Optional[http.client.HTTPConnection] = None

    # ----------------------------------------------------------- plumbing
    def _connection(self) -> http.client.HTTPConnection:
        if self._conn is None:
            self._conn = http.client.HTTPConnection(
                self.host, self.port, timeout=self.timeout_s)
        return self._conn

    def close(self) -> None:
        if self._conn is not None:
            try:
                self._conn.close()
            finally:
                self._conn = None

    def __enter__(self) -> "EdgeClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def _request(self, method: str, path: str, body=None,
                 headers: Optional[dict] = None):
        """One round trip; reconnects once when the SEND fails on a
        stale keep-alive socket (the server may close between
        requests while draining). A failure after the request was
        sent is never retried — the server may have admitted the
        work, and a blind resend would double-submit a
        non-idempotent POST. Returns (status, headers, parsed-body).
        """
        payload = None if body is None else proto.dumps(body)
        hdrs = dict(headers or {})
        if payload is not None:
            hdrs.setdefault("Content-Type", "application/json")
        for attempt in (0, 1):
            conn = self._connection()
            try:
                conn.request(method, path, body=payload, headers=hdrs)
            except (http.client.HTTPException, ConnectionError,
                    BrokenPipeError, socket.timeout, OSError):
                self.close()
                if attempt:
                    raise
                continue
            try:
                resp = conn.getresponse()
                raw = resp.read()
                break
            except BaseException:
                # The request is on the wire: whatever happened
                # (timeout, reset), resending is not safe.
                self.close()
                raise
        ctype = resp.getheader("Content-Type", "")
        if resp.getheader("Connection", "").lower() == "close":
            self.close()
        if ctype.startswith("application/json"):
            try:
                parsed = json.loads(raw) if raw else {}
            except ValueError:
                parsed = {"raw": raw.decode("utf-8", "replace")}
        else:
            parsed = raw
        return resp.status, dict(resp.getheaders()), parsed

    def _checked(self, method: str, path: str, body=None,
                 headers: Optional[dict] = None) -> dict:
        status, hdrs, parsed = self._request(method, path, body, headers)
        if status != 200:
            err = EdgeError(status, parsed if isinstance(parsed, dict)
                            else None)
            ra = {k.lower(): v for k, v in hdrs.items()}.get(
                "retry-after")
            if ra is not None:
                try:
                    err.retry_after_s = int(ra)
                except ValueError:
                    pass
            raise err
        return parsed

    # ------------------------------------------------------------ endpoints
    def healthz(self) -> dict:
        status, _hdrs, parsed = self._request("GET", "/healthz")
        if not isinstance(parsed, dict):
            raise EdgeError(status, message="healthz returned non-JSON")
        parsed["_status"] = status
        return parsed

    def metrics_text(self) -> str:
        status, _hdrs, parsed = self._request("GET", "/metrics")
        if status != 200:
            raise EdgeError(status, parsed if isinstance(parsed, dict)
                            else None)
        return (parsed if isinstance(parsed, str)
                else bytes(parsed).decode("utf-8"))

    def specialize(self, betas) -> str:
        out = self._checked("POST", "/v1/specialize",
                            {"betas": proto.encode_array(betas)})
        return out["subject"]

    def forward(self, pose, shape=None, subject: Optional[str] = None,
                *, priority: int = 0,
                deadline_s: Optional[float] = None) -> np.ndarray:
        """One-shot forward through the wire; mirrors
        ``ServingEngine.forward``. Raises ``EdgeError`` with the
        server's structured kind (shed -> status 429 with
        ``retry_after_s`` populated)."""
        body = {"pose": proto.encode_array(pose)}
        if shape is not None:
            body["shape"] = proto.encode_array(shape)
        if subject is not None:
            body["subject"] = subject
        headers = {proto.PRIORITY_HEADER: str(int(priority))}
        if deadline_s is not None:
            headers[proto.DEADLINE_HEADER] = repr(float(deadline_s))
        out = self._checked("POST", "/v1/forward", body, headers)
        return proto.decode_array(out["verts"])

    # -------------------------------------------------------------- streams
    def open_stream(self, *, subject: Optional[str] = None,
                    betas=None, frame_deadline_s: Optional[float] = None,
                    idle_timeout_s: Optional[float] = None,
                    resume_pose=None, **open_kw) -> "EdgeStreamClient":
        """Open a PR-12 stream over a DEDICATED upgraded connection
        (the session is connection-affine; this client's one-shot
        connection stays usable beside it). ``resume_pose`` warm-starts
        the tracker — the PR-18 migration handoff over the wire."""
        return EdgeStreamClient(
            self.host, self.port, timeout_s=self.timeout_s,
            subject=subject, betas=betas,
            frame_deadline_s=frame_deadline_s,
            idle_timeout_s=idle_timeout_s, resume_pose=resume_pose,
            **open_kw)


class EdgeStreamClient:
    """One upgraded stream connection: open -> frame* -> close.

    ``abort()`` hard-closes the socket mid-stream — the disconnect the
    server must answer with ``future.cancel()`` + session close (the
    config18 disconnect leg drives exactly this)."""

    def __init__(self, host: str, port: int, *, timeout_s: float = 30.0,
                 subject: Optional[str] = None, betas=None,
                 frame_deadline_s: Optional[float] = None,
                 idle_timeout_s: Optional[float] = None,
                 resume_pose=None, **open_kw):
        if (subject is None) == (betas is None):
            raise ValueError("pass exactly one of subject= / betas=")
        self._sock = socket.create_connection((host, port),
                                              timeout=timeout_s)
        self._rfile = self._sock.makefile("rb")
        try:
            self._sock.sendall(
                (f"POST /v1/stream HTTP/1.1\r\n"
                 f"Host: {host}:{port}\r\n"
                 f"Upgrade: {proto.STREAM_UPGRADE}\r\n"
                 f"Connection: Upgrade\r\n"
                 f"Content-Length: 0\r\n\r\n").encode("latin-1"))
            status_line = self._rfile.readline()
            if not status_line.startswith(b"HTTP/1.1 101"):
                raise EdgeError(0, message=f"stream upgrade refused: "
                                           f"{status_line!r}")
            while True:                 # drain the 101 headers
                h = self._rfile.readline()
                if h in (b"\r\n", b"\n", b""):
                    break
            msg = {"op": "open"}
            if subject is not None:
                msg["subject"] = subject
            else:
                msg["betas"] = proto.encode_array(betas)
            if frame_deadline_s is not None:
                msg["frame_deadline_s"] = frame_deadline_s
            if idle_timeout_s is not None:
                msg["idle_timeout_s"] = idle_timeout_s
            if resume_pose is not None:
                msg["resume_pose"] = proto.encode_array(resume_pose)
            msg.update(open_kw)
            reply = self._roundtrip(msg)
            if "error" in reply:
                raise EdgeError(0, reply,
                                message=f"stream open refused: "
                                        f"{reply['error']}")
            self.stream_id = reply.get("stream_id")
            self.subject = reply.get("subject")
        except BaseException:
            self.abort()
            raise

    def _roundtrip(self, msg: dict) -> dict:
        self._sock.sendall(proto.dumps(msg) + b"\n")
        line = self._rfile.readline()
        if not line:
            raise EdgeError(0, message="stream connection closed by "
                                       "the server")
        return json.loads(line)

    def frame(self, target, *,
              deadline_s: Optional[float] = None) -> FrameReply:
        """One frame through the wire; raises ``EdgeError`` carrying
        the per-frame structured kind (shed/expired keep the stream
        open — retry or close is the caller's call)."""
        msg = {"op": "frame", "target": proto.encode_array(target)}
        if deadline_s is not None:
            msg["deadline_s"] = deadline_s
        reply = self._roundtrip(msg)
        if "error" in reply:
            raise EdgeError(0, reply,
                            message=f"frame failed: {reply['error']}")
        return FrameReply(
            pose=proto.decode_array(reply["pose"]),
            verts=proto.decode_array(reply["verts"]),
            fit_loss=float(reply["fit_loss"]),
            frame=int(reply["frame"]),
        )

    def close(self) -> Optional[dict]:
        """Protocol close (the polite path); returns the server's
        closed event, or None if the socket is already gone."""
        try:
            reply = self._roundtrip({"op": "close"})
        except (EdgeError, OSError, ValueError):
            reply = None
        self.abort()
        return reply

    def abort(self) -> None:
        """Hard-close the socket WITHOUT the close op — the abrupt
        client disappearance the server's disconnect handler exists
        for. ``shutdown`` first: a bare ``close()`` on a socket with a
        live ``makefile`` only drops an io-ref (no FIN reaches the
        server), and it also unblocks a sibling thread parked in
        ``frame()``'s readline."""
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        for closer in (self._rfile.close, self._sock.close):
            try:
                closer()
            except OSError:
                pass

    def __enter__(self) -> "EdgeStreamClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class ResilientStream:
    """Reconnect-and-resume stream client (PR 20): the PR-18
    last-confirmed-pose protocol applied at the CLIENT edge.

    The proxy's ``_StreamRelay`` survives a WORKER death for the
    client; nothing survives the death of the proxy itself — the
    socket dies and the relay's state dies with it. This wrapper keeps
    that state (the original open identity, the last CONFIRMED pose,
    the confirmed-frame count) on the client side and, when the
    transport dies mid-op, reconnects to the SAME host:port (the
    pair's stable service port — the flock winner binds it), re-opens
    with ``resume_pose=<last confirmed pose>``, and re-sends the
    in-flight frame. Re-sending is safe for exactly the relay's
    reason: the lost reply never reached us (one reply line per op,
    strictly ordered), and a deterministic fit warm-started from the
    same confirmed pose re-derives the SAME result. Frame numbers stay
    continuous: the resumed session counts from 0 again and every
    reply gets the confirmed-count offset added.

    Reconnects are BOUNDED (attempt cap + deadline + doubling
    backoff) and classified: a transport death retries, a structured
    server refusal (shed/expired/bad request) raises immediately —
    the stream is alive and the refusal is the caller's business.
    """

    def __init__(self, host: str, port: int, *,
                 timeout_s: float = 30.0,
                 subject: Optional[str] = None, betas=None,
                 max_reconnects: int = 8,
                 reconnect_backoff_s: float = 0.1,
                 reconnect_timeout_s: float = 30.0,
                 **open_kw):
        self.host = host
        self.port = int(port)
        self.timeout_s = float(timeout_s)
        self._subject = subject
        self._betas = betas
        self._open_kw = dict(open_kw)
        self.max_reconnects = int(max_reconnects)
        self.reconnect_backoff_s = float(reconnect_backoff_s)
        self.reconnect_timeout_s = float(reconnect_timeout_s)
        self.reconnects = 0             # successful session re-opens
        self._last_pose: Optional[np.ndarray] = None
        self._frames_confirmed = 0
        self._offset = 0
        self._stream = self._dial(resume=False)

    # ----------------------------------------------------------- plumbing
    def _dial(self, *, resume: bool) -> EdgeStreamClient:
        kw = dict(self._open_kw)
        if resume and self._last_pose is not None:
            kw["resume_pose"] = self._last_pose
        return EdgeStreamClient(
            self.host, self.port, timeout_s=self.timeout_s,
            subject=self._subject, betas=self._betas, **kw)

    def _reconnect(self, cause: BaseException) -> None:
        """Bounded re-dial of the SAME address with resume state; on
        exhaustion raises an ``EdgeError`` that names both the
        original death and the last reconnect failure."""
        try:
            self._stream.abort()
        except Exception:  # noqa: BLE001 — already dead
            pass
        import time

        deadline = time.monotonic() + self.reconnect_timeout_s
        delay = self.reconnect_backoff_s
        attempt = 0
        last: BaseException = cause
        while True:
            attempt += 1
            try:
                self._stream = self._dial(resume=True)
                break
            except (EdgeError, OSError, ConnectionError,
                    ValueError) as e:
                last = e
                if (attempt >= self.max_reconnects
                        or time.monotonic() >= deadline):
                    raise EdgeError(0, message=(
                        f"stream lost ({type(cause).__name__}: {cause})"
                        f" and reconnect exhausted after {attempt} "
                        f"attempt(s): {type(last).__name__}: {last}"
                    )) from cause
                time.sleep(min(delay,
                               max(0.0, deadline - time.monotonic())))
                delay *= 2.0
        self._offset = self._frames_confirmed
        self.reconnects += 1

    # ------------------------------------------------------------- surface
    @property
    def stream_id(self):
        return self._stream.stream_id

    @property
    def subject(self):
        return self._stream.subject

    def frame(self, target, *,
              deadline_s: Optional[float] = None) -> FrameReply:
        msg = {"op": "frame", "target": proto.encode_array(target)}
        if deadline_s is not None:
            msg["deadline_s"] = deadline_s
        while True:
            try:
                # _roundtrip raises ONLY on transport death (closed
                # socket / timeout / torn line); structured refusals
                # come back as a reply dict and are never retried.
                reply = self._stream._roundtrip(msg)
                break
            except (EdgeError, OSError, ConnectionError,
                    ValueError) as e:
                self._reconnect(e)      # raises when exhausted
        if "error" in reply:
            raise EdgeError(0, reply,
                            message=f"frame failed: {reply['error']}")
        out = FrameReply(
            pose=proto.decode_array(reply["pose"]),
            verts=proto.decode_array(reply["verts"]),
            fit_loss=float(reply["fit_loss"]),
            frame=int(reply["frame"]) + self._offset,
        )
        self._last_pose = out.pose
        self._frames_confirmed = out.frame + 1
        return out

    def close(self) -> Optional[dict]:
        reply = self._stream.close()
        if isinstance(reply, dict) and "frames" in reply:
            reply["frames"] = int(reply["frames"]) + self._offset
        return reply

    def abort(self) -> None:
        self._stream.abort()

    def __enter__(self) -> "ResilientStream":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
