"""Loopback/test client for the edge wire protocol (stdlib-only).

The config18 drill, the edge tests, and `mano status --server` all
speak to a live edge through THIS module, so the bytes the server is
judged against are produced by one shared implementation (the
protocol.py single-owner rule). It is deliberately synchronous —
drill workers are threads with one persistent connection each, the
shape real load-generator fleets take.

Every call is BOUNDED: the socket timeout covers connect and each
read, so a wedged server degrades to a structured ``EdgeError``
(never a hang — the `mano status` probe contract).
"""

from __future__ import annotations

import http.client
import json
import socket
from typing import NamedTuple, Optional

import numpy as np

from mano_hand_tpu.edge import protocol as proto


class EdgeError(RuntimeError):
    """A structured edge failure: HTTP status + the server's
    kind/phase/message error body (+ Retry-After when the server sent
    backpressure)."""

    def __init__(self, status: int, body: Optional[dict] = None,
                 message: str = ""):
        err = (body or {}).get("error") or {}
        self.status = int(status)
        self.kind = err.get("kind", "error")
        self.phase = err.get("phase", "edge")
        self.flight = (body or {}).get("flight")
        self.retry_after_s: Optional[int] = None
        super().__init__(
            message or f"edge {status}: [{self.kind}] "
                       f"{err.get('message', '')}")


class FrameReply(NamedTuple):
    """One wire stream frame (mirrors serving.streams.FrameResult)."""

    pose: np.ndarray
    verts: np.ndarray
    fit_loss: float
    frame: int


class EdgeClient:
    """One persistent HTTP/1.1 connection to an edge worker.

    Thread-compatible, not thread-safe: one client per worker thread
    (the persistent-connection-per-worker shape). ``timeout_s`` bounds
    connect and every read.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 8080,
                 *, timeout_s: float = 30.0):
        self.host = host
        self.port = int(port)
        self.timeout_s = float(timeout_s)
        self._conn: Optional[http.client.HTTPConnection] = None

    # ----------------------------------------------------------- plumbing
    def _connection(self) -> http.client.HTTPConnection:
        if self._conn is None:
            self._conn = http.client.HTTPConnection(
                self.host, self.port, timeout=self.timeout_s)
        return self._conn

    def close(self) -> None:
        if self._conn is not None:
            try:
                self._conn.close()
            finally:
                self._conn = None

    def __enter__(self) -> "EdgeClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def _request(self, method: str, path: str, body=None,
                 headers: Optional[dict] = None):
        """One round trip; reconnects once when the SEND fails on a
        stale keep-alive socket (the server may close between
        requests while draining). A failure after the request was
        sent is never retried — the server may have admitted the
        work, and a blind resend would double-submit a
        non-idempotent POST. Returns (status, headers, parsed-body).
        """
        payload = None if body is None else proto.dumps(body)
        hdrs = dict(headers or {})
        if payload is not None:
            hdrs.setdefault("Content-Type", "application/json")
        for attempt in (0, 1):
            conn = self._connection()
            try:
                conn.request(method, path, body=payload, headers=hdrs)
            except (http.client.HTTPException, ConnectionError,
                    BrokenPipeError, socket.timeout, OSError):
                self.close()
                if attempt:
                    raise
                continue
            try:
                resp = conn.getresponse()
                raw = resp.read()
                break
            except BaseException:
                # The request is on the wire: whatever happened
                # (timeout, reset), resending is not safe.
                self.close()
                raise
        ctype = resp.getheader("Content-Type", "")
        if resp.getheader("Connection", "").lower() == "close":
            self.close()
        if ctype.startswith("application/json"):
            try:
                parsed = json.loads(raw) if raw else {}
            except ValueError:
                parsed = {"raw": raw.decode("utf-8", "replace")}
        else:
            parsed = raw
        return resp.status, dict(resp.getheaders()), parsed

    def _checked(self, method: str, path: str, body=None,
                 headers: Optional[dict] = None) -> dict:
        status, hdrs, parsed = self._request(method, path, body, headers)
        if status != 200:
            err = EdgeError(status, parsed if isinstance(parsed, dict)
                            else None)
            ra = {k.lower(): v for k, v in hdrs.items()}.get(
                "retry-after")
            if ra is not None:
                try:
                    err.retry_after_s = int(ra)
                except ValueError:
                    pass
            raise err
        return parsed

    # ------------------------------------------------------------ endpoints
    def healthz(self) -> dict:
        status, _hdrs, parsed = self._request("GET", "/healthz")
        if not isinstance(parsed, dict):
            raise EdgeError(status, message="healthz returned non-JSON")
        parsed["_status"] = status
        return parsed

    def metrics_text(self) -> str:
        status, _hdrs, parsed = self._request("GET", "/metrics")
        if status != 200:
            raise EdgeError(status, parsed if isinstance(parsed, dict)
                            else None)
        return (parsed if isinstance(parsed, str)
                else bytes(parsed).decode("utf-8"))

    def specialize(self, betas) -> str:
        out = self._checked("POST", "/v1/specialize",
                            {"betas": proto.encode_array(betas)})
        return out["subject"]

    def forward(self, pose, shape=None, subject: Optional[str] = None,
                *, priority: int = 0,
                deadline_s: Optional[float] = None) -> np.ndarray:
        """One-shot forward through the wire; mirrors
        ``ServingEngine.forward``. Raises ``EdgeError`` with the
        server's structured kind (shed -> status 429 with
        ``retry_after_s`` populated)."""
        body = {"pose": proto.encode_array(pose)}
        if shape is not None:
            body["shape"] = proto.encode_array(shape)
        if subject is not None:
            body["subject"] = subject
        headers = {proto.PRIORITY_HEADER: str(int(priority))}
        if deadline_s is not None:
            headers[proto.DEADLINE_HEADER] = repr(float(deadline_s))
        out = self._checked("POST", "/v1/forward", body, headers)
        return proto.decode_array(out["verts"])

    # -------------------------------------------------------------- streams
    def open_stream(self, *, subject: Optional[str] = None,
                    betas=None, frame_deadline_s: Optional[float] = None,
                    idle_timeout_s: Optional[float] = None,
                    resume_pose=None, **open_kw) -> "EdgeStreamClient":
        """Open a PR-12 stream over a DEDICATED upgraded connection
        (the session is connection-affine; this client's one-shot
        connection stays usable beside it). ``resume_pose`` warm-starts
        the tracker — the PR-18 migration handoff over the wire."""
        return EdgeStreamClient(
            self.host, self.port, timeout_s=self.timeout_s,
            subject=subject, betas=betas,
            frame_deadline_s=frame_deadline_s,
            idle_timeout_s=idle_timeout_s, resume_pose=resume_pose,
            **open_kw)


class EdgeStreamClient:
    """One upgraded stream connection: open -> frame* -> close.

    ``abort()`` hard-closes the socket mid-stream — the disconnect the
    server must answer with ``future.cancel()`` + session close (the
    config18 disconnect leg drives exactly this)."""

    def __init__(self, host: str, port: int, *, timeout_s: float = 30.0,
                 subject: Optional[str] = None, betas=None,
                 frame_deadline_s: Optional[float] = None,
                 idle_timeout_s: Optional[float] = None,
                 resume_pose=None, **open_kw):
        if (subject is None) == (betas is None):
            raise ValueError("pass exactly one of subject= / betas=")
        self._sock = socket.create_connection((host, port),
                                              timeout=timeout_s)
        self._rfile = self._sock.makefile("rb")
        try:
            self._sock.sendall(
                (f"POST /v1/stream HTTP/1.1\r\n"
                 f"Host: {host}:{port}\r\n"
                 f"Upgrade: {proto.STREAM_UPGRADE}\r\n"
                 f"Connection: Upgrade\r\n"
                 f"Content-Length: 0\r\n\r\n").encode("latin-1"))
            status_line = self._rfile.readline()
            if not status_line.startswith(b"HTTP/1.1 101"):
                raise EdgeError(0, message=f"stream upgrade refused: "
                                           f"{status_line!r}")
            while True:                 # drain the 101 headers
                h = self._rfile.readline()
                if h in (b"\r\n", b"\n", b""):
                    break
            msg = {"op": "open"}
            if subject is not None:
                msg["subject"] = subject
            else:
                msg["betas"] = proto.encode_array(betas)
            if frame_deadline_s is not None:
                msg["frame_deadline_s"] = frame_deadline_s
            if idle_timeout_s is not None:
                msg["idle_timeout_s"] = idle_timeout_s
            if resume_pose is not None:
                msg["resume_pose"] = proto.encode_array(resume_pose)
            msg.update(open_kw)
            reply = self._roundtrip(msg)
            if "error" in reply:
                raise EdgeError(0, reply,
                                message=f"stream open refused: "
                                        f"{reply['error']}")
            self.stream_id = reply.get("stream_id")
            self.subject = reply.get("subject")
        except BaseException:
            self.abort()
            raise

    def _roundtrip(self, msg: dict) -> dict:
        self._sock.sendall(proto.dumps(msg) + b"\n")
        line = self._rfile.readline()
        if not line:
            raise EdgeError(0, message="stream connection closed by "
                                       "the server")
        return json.loads(line)

    def frame(self, target, *,
              deadline_s: Optional[float] = None) -> FrameReply:
        """One frame through the wire; raises ``EdgeError`` carrying
        the per-frame structured kind (shed/expired keep the stream
        open — retry or close is the caller's call)."""
        msg = {"op": "frame", "target": proto.encode_array(target)}
        if deadline_s is not None:
            msg["deadline_s"] = deadline_s
        reply = self._roundtrip(msg)
        if "error" in reply:
            raise EdgeError(0, reply,
                            message=f"frame failed: {reply['error']}")
        return FrameReply(
            pose=proto.decode_array(reply["pose"]),
            verts=proto.decode_array(reply["verts"]),
            fit_loss=float(reply["fit_loss"]),
            frame=int(reply["frame"]),
        )

    def close(self) -> Optional[dict]:
        """Protocol close (the polite path); returns the server's
        closed event, or None if the socket is already gone."""
        try:
            reply = self._roundtrip({"op": "close"})
        except (EdgeError, OSError, ValueError):
            reply = None
        self.abort()
        return reply

    def abort(self) -> None:
        """Hard-close the socket WITHOUT the close op — the abrupt
        client disappearance the server's disconnect handler exists
        for. ``shutdown`` first: a bare ``close()`` on a socket with a
        live ``makefile`` only drops an io-ref (no FIN reaches the
        server), and it also unblocks a sibling thread parked in
        ``frame()``'s readline."""
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        for closer in (self._rfile.close, self._sock.close):
            try:
                closer()
            except OSError:
                pass

    def __enter__(self) -> "EdgeStreamClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
