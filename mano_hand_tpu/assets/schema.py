"""The MANO parameter PyTree — the data contract between the asset layer and
the compute core.

Mirrors the nine-key pickle schema that is the reference's de-facto API
(/root/reference/dump_model.py:8-18 -> /root/reference/mano_np.py:20-33), but
as an immutable, jit/vmap/grad-friendly PyTree with static metadata
(kinematic tree, handedness) carried out-of-band so XLA sees only dense
arrays with static shapes.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Tuple

import jax
import numpy as np

from mano_hand_tpu import constants as C


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class ManoParams:
    """Frozen MANO model parameters.

    Array fields are PyTree leaves (np.ndarray or jax.Array); ``parents`` and
    ``side`` are static aux data so the FK unroll and caching stay static
    under ``jax.jit``.

    Shapes (V=778 verts, J=16 joints, S=10 shape dims, P=135 pose-basis dims):
      v_template     [V, 3]    rest-pose template mesh
      shape_basis    [V, 3, S] shape blendshapes ("shapedirs")
      pose_basis     [V, 3, P] pose-corrective blendshapes ("posedirs")
      j_regressor    [J, V]    joint regressor (dense)
      lbs_weights    [V, J]    linear-blend-skinning weights
      pca_basis      [45, 45]  finger-pose PCA basis, rows = components
      pca_mean       [45]      mean finger pose (flattened 15x3 axis-angle)
      faces          [F, 3]    triangle indices, 0-based int32
    """

    v_template: Any
    shape_basis: Any
    pose_basis: Any
    j_regressor: Any
    lbs_weights: Any
    pca_basis: Any
    pca_mean: Any
    faces: Any
    parents: Tuple[int, ...] = dataclasses.field(
        default=C.MANO_PARENTS, metadata={"static": True}
    )
    side: str = dataclasses.field(default=C.RIGHT, metadata={"static": True})

    # -- convenience views ---------------------------------------------------
    @property
    def n_verts(self) -> int:
        return self.v_template.shape[0]

    @property
    def n_joints(self) -> int:
        return self.j_regressor.shape[0]

    @property
    def n_shape(self) -> int:
        return self.shape_basis.shape[-1]

    def astype(self, dtype) -> "ManoParams":
        """Cast all float leaves to ``dtype`` (faces stay integer)."""
        def cast(name, x):
            if name == "faces":
                return x
            return x.astype(dtype)
        return dataclasses.replace(
            self, **{f: cast(f, getattr(self, f)) for f in ARRAY_FIELDS}
        )

    def device_put(self, sharding=None) -> "ManoParams":
        put = (lambda x: jax.device_put(x, sharding)) if sharding else jax.device_put
        return dataclasses.replace(
            self, **{f: put(getattr(self, f)) for f in ARRAY_FIELDS}
        )


ARRAY_FIELDS = (
    "v_template",
    "shape_basis",
    "pose_basis",
    "j_regressor",
    "lbs_weights",
    "pca_basis",
    "pca_mean",
    "faces",
)


def validate(p: ManoParams) -> ManoParams:
    """Shape/consistency check of the asset contract; returns ``p``.

    Raises ValueError with a precise message on any mismatch, so a bad asset
    fails at load time rather than as an XLA shape error deep in a trace.
    """
    v, j = p.v_template.shape[0], p.j_regressor.shape[0]
    s = p.shape_basis.shape[-1]
    expect = {
        "v_template": (v, 3),
        "shape_basis": (v, 3, s),
        "pose_basis": (v, 3, (j - 1) * 9),
        "j_regressor": (j, v),
        "lbs_weights": (v, j),
        "pca_basis": ((j - 1) * 3, (j - 1) * 3),
        "pca_mean": ((j - 1) * 3,),
    }
    for name, shape in expect.items():
        got = tuple(getattr(p, name).shape)
        if got != shape:
            raise ValueError(f"{name}: expected shape {shape}, got {got}")
    if p.faces.ndim != 2 or p.faces.shape[1] != 3:
        raise ValueError(f"faces: expected [F, 3], got {tuple(p.faces.shape)}")
    if len(p.parents) != j:
        raise ValueError(f"parents: expected length {j}, got {len(p.parents)}")
    if p.parents[0] != -1:
        raise ValueError("parents[0] must be -1 (root)")
    for i, par in enumerate(p.parents[1:], start=1):
        if not (0 <= par < i):
            raise ValueError(
                f"parents must be topologically ordered; parents[{i}]={par}"
            )
    faces = np.asarray(p.faces)
    if faces.size and (faces.min() < 0 or faces.max() >= v):
        raise ValueError(
            f"faces indices must be in [0, {v}); got range "
            f"[{faces.min()}, {faces.max()}]"
        )
    if p.side not in (C.LEFT, C.RIGHT, C.NEUTRAL):
        raise ValueError(
            f"side must be 'left', 'right' or 'neutral', got {p.side!r}")
    return p
