"""Asset I/O: official MANO pickles, reference-style dumped pickles, and the
canonical ``.npz`` form.

Covers both layers of the reference's asset pipeline:
  * C8 "asset converter" (/root/reference/dump_model.py:4-21): official
    chumpy-era pickle -> plain arrays (sparse J_regressor densified,
    root parent sentinel),
  * C1 "param loader" (/root/reference/mano_np.py:17-33): reads the dumped
    nine-key pickle.

We add a canonical ``.npz`` form (no pickle at runtime) and keep pickle paths
for interop with assets produced by the reference's own dump_model.py.
"""

from __future__ import annotations

import pickle
from pathlib import Path
from typing import Union

import numpy as np

from mano_hand_tpu import constants as C
from mano_hand_tpu.assets.schema import ARRAY_FIELDS, ManoParams, validate

PathLike = Union[str, Path]

_PICKLE_KEYS = {
    "pose_pca_basis": "pca_basis",
    "pose_pca_mean": "pca_mean",
    "J_regressor": "j_regressor",
    "skinning_weights": "lbs_weights",
    "mesh_pose_basis": "pose_basis",
    "mesh_shape_basis": "shape_basis",
    "mesh_template": "v_template",
    "faces": "faces",
}


class _StubPickled:
    """Stand-in for classes whose module is unimportable at unpickle time.

    The official MANO pickle holds ``chumpy.Ch`` wrappers
    (/root/reference/dump_model.py:6-10 runs in a chumpy-era env); chumpy is
    dead upstream and absent from modern images, so unpickling it must not
    require the real class. The stub absorbs any construction protocol
    pickle uses (``__setstate__`` dict, ``_reconstructor`` args) and exposes
    the wrapped ndarray the way ``_dense`` probes for it.
    """

    def __init__(self, *args, **kwargs):
        self._args = args
        self.__dict__.update(kwargs)

    def __setstate__(self, state):
        if isinstance(state, dict):
            self.__dict__.update(state)
        else:
            self._state = state

    def _arrays(self):
        return [v for v in self.__dict__.values()
                if isinstance(v, np.ndarray)]

    @property
    def r(self):
        # chumpy.Ch stores its value in attribute ``x``; fall back to the
        # largest ndarray in the state for chumpy subclasses that rename it.
        x = self.__dict__.get("x")
        if isinstance(x, np.ndarray):
            return x
        arrays = self._arrays()
        if not arrays:
            raise ValueError(
                f"stubbed pickle object has no array payload: "
                f"{sorted(self.__dict__)}"
            )
        return max(arrays, key=lambda a: a.size)


class _TolerantUnpickler(pickle.Unpickler):
    """Unpickler that substitutes ``_StubPickled`` for missing classes.

    Only loads what it can resolve for real and stubs the rest — asset
    pickles are still untrusted input, so this never fabricates imports,
    it only *narrows* what a normal ``pickle.load`` would execute.
    """

    def find_class(self, module, name):
        try:
            return super().find_class(module, name)
        except (ImportError, AttributeError):
            return _StubPickled


def _tolerant_load(f, encoding: str):
    return _TolerantUnpickler(f, encoding=encoding).load()


def _dense(a) -> np.ndarray:
    """Materialize chumpy arrays / scipy sparse matrices as dense ndarrays."""
    if hasattr(a, "toarray"):  # scipy sparse
        return np.asarray(a.toarray())
    if hasattr(a, "r"):  # chumpy Ch object (or its _StubPickled stand-in)
        return np.asarray(a.r)
    return np.asarray(a)


def _parents_from(raw) -> tuple:
    parents = list(raw)
    parents[0] = -1  # reference stores None (dump_model.py:18); we use -1
    return tuple(int(p) for p in parents)


def _infer_side(path: PathLike, explicit: str | None) -> str:
    if explicit is not None:
        return explicit
    name = Path(path).name.lower()
    # 'neutral' only marks an unsided asset when NO side marker is
    # present: a sided file whose name merely mentions neutral (e.g.
    # neutral_pose_left.pkl) must keep its handedness (ADVICE.md r5).
    if "neutral" in name and "left" not in name and "right" not in name:
        return C.NEUTRAL
    return C.LEFT if "left" in name else C.RIGHT


def load_dumped_pickle(path: PathLike, side: str | None = None) -> ManoParams:
    """Load an asset in the reference's dumped-pickle format (nine keys).

    Keys may be str or bytes: the reference reads its own dumps with
    ``encoding='bytes'`` (/root/reference/mano_np.py:18), so py2-era dumps
    with bytes keys are legitimate inputs.
    """
    with open(path, "rb") as f:
        raw = _tolerant_load(f, encoding="bytes")
    raw = {k.decode() if isinstance(k, bytes) else k: v for k, v in raw.items()}
    kwargs = {ours: _dense(raw[theirs]) for theirs, ours in _PICKLE_KEYS.items()}
    kwargs["faces"] = kwargs["faces"].astype(np.int32)
    return validate(
        ManoParams(
            parents=_parents_from(raw["parents"]),
            side=_infer_side(path, side),
            **kwargs,
        )
    )


def load_official_pickle(path: PathLike, side: str | None = None) -> ManoParams:
    """Load an official MANO_{LEFT,RIGHT}.pkl directly (chumpy-era pickle).

    Folds in the conversion the reference performs offline
    (/root/reference/dump_model.py:8-18): densify the sparse J_regressor,
    take row 0 of kintree_table as the parent array, and strip chumpy
    wrappers. Requires ``encoding='latin1'`` for the py2-era pickle.

    Works WITHOUT chumpy installed: unresolvable classes unpickle as
    ``_StubPickled``, whose ``.r`` hands ``_dense`` the wrapped array.
    """
    with open(path, "rb") as f:
        raw = _tolerant_load(f, encoding="latin1")
    return validate(
        ManoParams(
            v_template=_dense(raw["v_template"]).astype(np.float64),
            shape_basis=_dense(raw["shapedirs"]).astype(np.float64),
            pose_basis=_dense(raw["posedirs"]).astype(np.float64),
            j_regressor=_dense(raw["J_regressor"]).astype(np.float64),
            lbs_weights=_dense(raw["weights"]).astype(np.float64),
            pca_basis=_dense(raw["hands_components"]).astype(np.float64),
            pca_mean=_dense(raw["hands_mean"]).astype(np.float64),
            faces=_dense(raw["f"]).astype(np.int32),
            parents=_parents_from(_dense(raw["kintree_table"])[0]),
            side=_infer_side(path, side),
        )
    )


def load_smpl_pickle(path: PathLike, side: str | None = None) -> ManoParams:
    """Load an official SMPL-family body pickle (SMPL/SMPL-H style keys)
    into the same params PyTree the whole framework runs on.

    The compute core is topology-generic (level-parallel FK over any
    topologically-ordered tree, shape/pose blendshapes by contraction —
    see tests/test_generic_topology.py's 24-joint suite), so a body model
    is just a bigger asset: V=6890, J=24, P=207 for SMPL. The official
    pickle shares MANO's chumpy-era container (same tolerant unpickling,
    sparse J_regressor, ``kintree_table``) but carries no hand-pose PCA —
    we synthesize a pass-through PCA space (identity basis, zero mean,
    dims (J-1)*3) so every pose-PCA API keeps working and decodes to the
    coefficients themselves.

    Body assets are unsided (``side='neutral'``); SMPL's root parent
    arrives as uint32 ``2**32 - 1`` in ``kintree_table[0, 0]``, mapped to
    the -1 sentinel like the reference's ``None``
    (/root/reference/dump_model.py:18 semantics).
    """
    with open(path, "rb") as f:
        raw = _tolerant_load(f, encoding="latin1")
    j_reg = _dense(raw["J_regressor"]).astype(np.float64)
    j = j_reg.shape[0]
    n_aa = (j - 1) * 3
    return validate(
        ManoParams(
            v_template=_dense(raw["v_template"]).astype(np.float64),
            shape_basis=_dense(raw["shapedirs"]).astype(np.float64),
            pose_basis=_dense(raw["posedirs"]).astype(np.float64),
            j_regressor=j_reg,
            lbs_weights=_dense(raw["weights"]).astype(np.float64),
            pca_basis=np.eye(n_aa, dtype=np.float64),
            pca_mean=np.zeros(n_aa, dtype=np.float64),
            faces=_dense(raw["f"]).astype(np.int32),
            parents=_parents_from(
                _dense(raw["kintree_table"]).astype(np.int64)[0]),
            side=C.NEUTRAL if side is None else side,
        )
    )


def save_npz(params: ManoParams, path: PathLike) -> None:
    """Canonical on-disk form: a flat .npz, no pickle objects."""
    arrays = {f: np.asarray(getattr(params, f)) for f in ARRAY_FIELDS}
    np.savez(
        path,
        parents=np.asarray(params.parents, dtype=np.int32),
        side=np.asarray(params.side),
        **arrays,
    )


def load_npz(path: PathLike, side: str | None = None) -> ManoParams:
    with np.load(path) as z:
        arrays = {f: z[f] for f in ARRAY_FIELDS}
        arrays["faces"] = arrays["faces"].astype(np.int32)
        return validate(
            ManoParams(
                parents=tuple(int(p) for p in z["parents"]),
                side=side if side is not None else str(z["side"]),
                **arrays,
            )
        )


def save_dumped_pickle(params: ManoParams, path: PathLike) -> None:
    """Write the reference's dumped-pickle format for interop (C8 parity):
    the same nine keys /root/reference/mano_np.py:20-33 reads, including the
    ``parents[0] = None`` sentinel."""
    out = {theirs: np.asarray(getattr(params, ours))
           for theirs, ours in _PICKLE_KEYS.items()}
    parents = [None] + [int(p) for p in params.parents[1:]]
    out["parents"] = parents
    with open(path, "wb") as f:
        pickle.dump(out, f)


def load_model(path: PathLike, side: str | None = None) -> ManoParams:
    """Load an asset of any supported format, sniffed by extension/content."""
    p = Path(path)
    if p.suffix == ".npz":
        return load_npz(p, side=side)
    # All pickle flavors end in .pkl; sniff by content: reference-style
    # dumped keys, then official MANO (has hand-PCA keys), then
    # SMPL-family body (same container, no hand-PCA).
    try:
        return load_dumped_pickle(p, side=side)
    except (KeyError, UnicodeDecodeError):
        pass
    try:
        return load_official_pickle(p, side=side)
    except KeyError as e:
        # Fall through to the body loader ONLY when what's missing is the
        # hand-PCA pair — any other missing key is a corrupt official
        # pickle that must fail loudly, not load as a fabricated body.
        if e.args and e.args[0] not in ("hands_components", "hands_mean"):
            raise
        loaded = load_smpl_pickle(p, side=side)
        if loaded.n_joints == C.N_JOINTS:
            # A 16-joint asset without hand-PCA keys is a broken MANO
            # file, not a body model; identity-PCA would silently replace
            # the real MANO pose space. (load_smpl_pickle called directly
            # still accepts any topology.)
            raise KeyError(
                "hands_components/hands_mean missing from a 16-joint "
                "asset — corrupt MANO pickle? Use load_smpl_pickle "
                "explicitly to load it as a PCA-less body."
            ) from e
        return loaded
