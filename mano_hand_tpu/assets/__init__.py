from mano_hand_tpu.assets.mirror import mirror_params
from mano_hand_tpu.assets.schema import ManoParams, validate
from mano_hand_tpu.assets.synthetic import synthetic_pair, synthetic_params
from mano_hand_tpu.assets.loader import (
    load_dumped_pickle,
    load_model,
    load_npz,
    load_official_pickle,
    load_smpl_pickle,
    save_dumped_pickle,
    save_npz,
)
from mano_hand_tpu.assets.scans import (
    extract_scan_poses,
    mirror_pose,
    mirror_verts,
    save_scan_poses,
)

__all__ = [
    "ManoParams",
    "validate",
    "synthetic_params",
    "synthetic_pair",
    "load_model",
    "load_npz",
    "load_dumped_pickle",
    "load_official_pickle",
    "load_smpl_pickle",
    "save_npz",
    "save_dumped_pickle",
    "extract_scan_poses",
    "save_scan_poses",
    "mirror_params",
    "mirror_pose",
    "mirror_verts",
]
