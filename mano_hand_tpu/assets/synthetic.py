"""Deterministic synthetic MANO-shaped assets for tests and benchmarks.

The official MANO pickles are license-gated and absent from both the
reference repo (gitignored, /root/reference/.gitignore:1-5) and this one, so
every test/bench runs on a generated asset with the exact schema of
/root/reference/dump_model.py:8-18. The generator is seeded and pure NumPy,
so golden digests are stable across machines.
"""

from __future__ import annotations

import numpy as np

from mano_hand_tpu import constants as C
from mano_hand_tpu.assets.schema import ManoParams, validate


def synthetic_params(
    seed: int = 0,
    side: str = C.RIGHT,
    n_verts: int = C.N_VERTS,
    n_joints: int = C.N_JOINTS,
    n_shape: int = C.N_SHAPE,
    n_faces: int = C.N_FACES,
    dtype=np.float64,
) -> ManoParams:
    """Build a random but structurally valid MANO-like asset.

    Properties the real asset has and tests rely on:
      * j_regressor rows are non-negative and sum to 1 (joints are convex
        combinations of vertices),
      * lbs_weights rows are non-negative and sum to 1, concentrated on a
        few joints,
      * pca_basis is orthonormal (rows = components),
      * parents is the true MANO kinematic tree when n_joints == 16.
    """
    rng = np.random.default_rng(seed)
    n_pose_aa = (n_joints - 1) * 3
    n_pose_basis = (n_joints - 1) * 9

    # A blobby hand-scale (~10 cm) point cloud as the template.
    v_template = rng.normal(scale=0.04, size=(n_verts, 3))
    v_template[:, 1] += np.linspace(0.0, 0.1, n_verts)  # stretch along +y

    shape_basis = rng.normal(scale=5e-3, size=(n_verts, 3, n_shape))
    pose_basis = rng.normal(scale=5e-4, size=(n_verts, 3, n_pose_basis))

    # Joint regressor: each joint draws from a random vertex neighborhood.
    j_regressor = rng.random((n_joints, n_verts)) ** 8  # sparse-ish
    j_regressor /= j_regressor.sum(axis=1, keepdims=True)

    # Skinning weights: concentrate each vertex on ~2 joints.
    lbs_weights = rng.random((n_verts, n_joints)) ** 6
    lbs_weights /= lbs_weights.sum(axis=1, keepdims=True)

    # Orthonormal PCA basis via QR; small mean pose.
    q, _ = np.linalg.qr(rng.normal(size=(n_pose_aa, n_pose_aa)))
    pca_basis = q
    pca_mean = rng.normal(scale=0.05, size=(n_pose_aa,))

    # Random valid triangles (distinct vertex ids per face).
    faces = np.stack(
        [rng.choice(n_verts, size=3, replace=False) for _ in range(n_faces)]
    ).astype(np.int32)

    if n_joints == C.N_JOINTS:
        parents = C.MANO_PARENTS
    else:
        parents = (-1,) + tuple(rng.integers(0, i) for i in range(1, n_joints))

    return validate(
        ManoParams(
            v_template=v_template.astype(dtype),
            shape_basis=shape_basis.astype(dtype),
            pose_basis=pose_basis.astype(dtype),
            j_regressor=j_regressor.astype(dtype),
            lbs_weights=lbs_weights.astype(dtype),
            pca_basis=pca_basis.astype(dtype),
            pca_mean=pca_mean.astype(dtype),
            faces=faces,
            parents=parents,
            side=side,
        )
    )


def synthetic_pair(seed: int = 0, dtype=np.float64):
    """A (left, right) pair of synthetic hands, distinct but seeded."""
    return (
        synthetic_params(seed=seed + 1, side=C.LEFT, dtype=dtype),
        synthetic_params(seed=seed, side=C.RIGHT, dtype=dtype),
    )
