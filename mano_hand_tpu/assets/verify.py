"""Truth anchors for loaded MANO assets (``cli verify``).

The official MANO pickles are license-gated and absent from this
environment, so the chumpy-stub unpickler (loader.py:load_official_pickle)
can only ever be exercised on synthetic replicas here. This module gives a
user with the licensed file an immediate verdict the moment they run
``python -m mano_hand_tpu verify MANO_RIGHT.pkl``:

- **gates** (hard failures): the public structural facts of MANO — 778
  vertices, 1538 faces, 16 joints, 45-dim finger-pose space, 10 shape
  dims, the 3-per-finger kinematic tree (constants.MANO_PARENTS) — plus
  invariants any genuine skinning model satisfies (LBS weight rows and
  joint-regressor rows are convex combinations; faces index the full
  vertex range; the f64 oracle forward is finite at the rest pose).
- **checks** (warnings): hand-scale bounding box, near-orthogonal PCA
  basis, manifold edges, all vertices referenced — properties the
  official asset has but a re-export might legitimately perturb.
- **digests**: canonical SHA-256 per decoded array (f64 bytes with a
  shape header) and one combined digest, printed so the result can be
  compared against any independently verified copy; ``--golden`` diffs
  two assets numerically, ``--expect`` pins the combined digest in CI.

Parity root: the reference trusts its pickles blindly
(/root/reference/mano_np.py:20-33 reads the dict with no validation;
/root/reference/dump_model.py:6-10 documents the manual download) — this
subsystem is the TPU-framework replacement for "it worked on my pickle".
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
from typing import List, Optional, Tuple

import numpy as np

from mano_hand_tpu import constants as C
from mano_hand_tpu.assets.loader import load_model
from mano_hand_tpu.assets.schema import ARRAY_FIELDS, ManoParams

# Public structural facts of the official MANO release (counts are in the
# MANO paper and every open-source consumer; see SURVEY.md §2 C1).
OFFICIAL = {
    "n_verts": 778,
    "n_faces": 1538,
    "n_joints": 16,
    "n_shape": 10,
    "n_pose_basis": 135,
    "pca_dims": 45,
}

# Combined digests of independently verified official assets, keyed by
# side. Empty by construction: the license forbids shipping anything
# derived from the asset, digests included, without the user's own copy.
# Populate locally (or pass --expect) after verifying a download once.
KNOWN_DIGESTS: dict = {}


@dataclasses.dataclass(frozen=True)
class Finding:
    level: str      # "gate" | "check"
    name: str
    ok: bool
    detail: str


@dataclasses.dataclass(frozen=True)
class VerifyReport:
    findings: Tuple[Finding, ...]
    digests: dict           # field -> sha256 hex; plus "combined"
    side: str

    @property
    def gates_ok(self) -> bool:
        return all(f.ok for f in self.findings if f.level == "gate")

    @property
    def warnings(self) -> List[Finding]:
        return [f for f in self.findings if f.level == "check" and not f.ok]


def _digest(arr: np.ndarray) -> str:
    """Canonical SHA-256: f64 (int64 for faces) C-order bytes, shape-tagged
    so e.g. a transposed regressor cannot collide."""
    arr = np.asarray(arr)
    a = np.ascontiguousarray(
        arr,
        dtype=np.int64 if np.issubdtype(arr.dtype, np.integer)
        else np.float64,
    )
    h = hashlib.sha256()
    h.update(repr((a.shape, a.dtype.str)).encode())
    h.update(a.tobytes())
    return h.hexdigest()


def compute_digests(p: ManoParams) -> dict:
    digests = {f: _digest(getattr(p, f)) for f in ARRAY_FIELDS}
    combined = hashlib.sha256(
        "".join(f"{k}:{digests[k]};" for k in sorted(digests)).encode()
    ).hexdigest()
    digests["combined"] = combined
    return digests


def _structure_gates(p: ManoParams, out: List[Finding]) -> None:
    def gate(name, ok, detail):
        out.append(Finding("gate", name, bool(ok), detail))

    gate("n_verts", p.n_verts == OFFICIAL["n_verts"],
         f"{p.n_verts} (official {OFFICIAL['n_verts']})")
    gate("n_faces", p.faces.shape[0] == OFFICIAL["n_faces"],
         f"{p.faces.shape[0]} (official {OFFICIAL['n_faces']})")
    gate("n_joints", p.n_joints == OFFICIAL["n_joints"],
         f"{p.n_joints} (official {OFFICIAL['n_joints']})")
    gate("n_shape", p.n_shape == OFFICIAL["n_shape"],
         f"{p.n_shape} (official {OFFICIAL['n_shape']})")
    gate("n_pose_basis",
         p.pose_basis.shape[-1] == OFFICIAL["n_pose_basis"],
         f"{p.pose_basis.shape[-1]} (official {OFFICIAL['n_pose_basis']})")
    gate("pca_dims", p.pca_basis.shape == (OFFICIAL["pca_dims"],) * 2,
         f"{tuple(p.pca_basis.shape)} "
         f"(official {(OFFICIAL['pca_dims'],) * 2})")
    gate("kinematic_tree", tuple(p.parents) == C.MANO_PARENTS,
         "3-joints-per-finger MANO tree"
         if tuple(p.parents) == C.MANO_PARENTS
         else f"parents={tuple(p.parents)}")


def _numeric_gates(p: ManoParams, out: List[Finding]) -> None:
    def gate(name, ok, detail):
        out.append(Finding("gate", name, bool(ok), detail))

    w = np.asarray(p.lbs_weights, np.float64)
    row_err = float(np.abs(w.sum(axis=1) - 1.0).max())
    gate("lbs_rows_sum_to_1", row_err < 1e-4,
         f"max |row sum - 1| = {row_err:.2e}")
    gate("lbs_nonnegative", float(w.min()) > -1e-6,
         f"min weight = {float(w.min()):.2e}")

    jr = np.asarray(p.j_regressor, np.float64)
    jr_err = float(np.abs(jr.sum(axis=1) - 1.0).max())
    gate("jreg_rows_sum_to_1", jr_err < 1e-4,
         f"max |row sum - 1| = {jr_err:.2e}")

    finite = all(
        np.isfinite(np.asarray(getattr(p, f))).all()
        for f in ARRAY_FIELDS if f != "faces"
    )
    gate("all_finite", finite, "every float field finite"
         if finite else "non-finite values present")

    # f64 oracle forward at rest pose: the end-to-end decode actually
    # produces a hand (finite verts, regressed root joint inside the
    # template bounding box).
    from mano_hand_tpu.models import oracle

    res = oracle.forward(p.astype(np.float64))
    v = np.asarray(res.verts)
    ok = bool(np.isfinite(v).all())
    lo, hi = np.asarray(p.v_template).min(0), np.asarray(p.v_template).max(0)
    root = np.asarray(res.joints)[0]
    inside = bool((root >= lo - 1e-6).all() and (root <= hi + 1e-6).all())
    gate("oracle_rest_forward", ok and inside,
         f"rest verts finite={ok}, root joint inside template bbox="
         f"{inside}")


def _quality_checks(p: ManoParams, out: List[Finding]) -> None:
    def check(name, ok, detail):
        out.append(Finding("check", name, bool(ok), detail))

    vt = np.asarray(p.v_template, np.float64)
    diag = float(np.linalg.norm(vt.max(0) - vt.min(0)))
    check("hand_scale", 0.05 < diag < 0.6,
          f"template bbox diagonal {diag * 100:.1f} cm "
          "(a hand is ~10-25 cm)")

    pb = np.asarray(p.pca_basis, np.float64)
    gram = pb @ pb.T
    off = gram - np.diag(np.diag(gram))
    scale = max(float(np.abs(np.diag(gram)).max()), 1e-12)
    ortho = float(np.abs(off).max()) / scale
    check("pca_near_orthogonal", ortho < 1e-3,
          f"max off-diag Gram / max diag = {ortho:.2e}")

    faces = np.asarray(p.faces)
    used = np.zeros(p.n_verts, bool)
    used[faces.ravel()] = True
    check("all_verts_referenced", bool(used.all()),
          f"{int(used.sum())}/{p.n_verts} vertices appear in faces")

    edges = np.sort(
        np.concatenate([faces[:, [0, 1]], faces[:, [1, 2]],
                        faces[:, [2, 0]]]), axis=1)
    _, counts = np.unique(edges, axis=0, return_counts=True)
    nonmanifold = int((counts > 2).sum())
    check("manifold_edges", nonmanifold == 0,
          f"{nonmanifold} edges shared by >2 faces")


def verify_asset(path, side: Optional[str] = None,
                 golden=None) -> VerifyReport:
    """Load ``path`` through the standard loader stack and audit it.

    golden: optional second asset path; decoded arrays are diffed
    numerically (gate: max |delta| < 1e-9 — byte-level agreement of two
    copies of the same official file, format conversions included).
    """
    p = load_model(path, side=side)
    findings: List[Finding] = []
    _structure_gates(p, findings)
    _numeric_gates(p, findings)
    _quality_checks(p, findings)
    digests = compute_digests(p)

    known = KNOWN_DIGESTS.get(p.side)
    if known is not None:
        findings.append(Finding(
            "gate", "known_digest", digests["combined"] == known,
            f"combined {digests['combined'][:16]}... vs known "
            f"{known[:16]}..."))

    if golden is not None:
        g = load_model(golden, side=side)
        worst = ("", 0.0)
        for f in ARRAY_FIELDS:
            a = np.asarray(getattr(p, f), np.float64)
            b = np.asarray(getattr(g, f), np.float64)
            if a.shape != b.shape:
                worst = (f, float("inf"))
                break
            d = float(np.abs(a - b).max()) if a.size else 0.0
            if d > worst[1]:
                worst = (f, d)
        findings.append(Finding(
            "gate", "matches_golden", worst[1] < 1e-9,
            f"max |delta| = {worst[1]:.3g} ({worst[0] or 'all fields'})"
            if np.isfinite(worst[1])
            else f"shape mismatch in {worst[0]}"))

    return VerifyReport(tuple(findings), digests, p.side)


def format_report(report: VerifyReport, path,
                  expect: Optional[str] = None) -> Tuple[str, int]:
    """Human-readable report + process return code (0 ok / 1 gate fail)."""
    lines = [f"verify {path} (side={report.side})"]
    for f in report.findings:
        mark = "PASS" if f.ok else ("FAIL" if f.level == "gate" else "WARN")
        lines.append(f"  [{mark}] {f.name}: {f.detail}")
    lines.append("  digests (sha256 of canonical f64 decode):")
    for k in sorted(report.digests):
        if k != "combined":
            lines.append(f"    {k}: {report.digests[k]}")
    lines.append(f"    combined: {report.digests['combined']}")
    ok = report.gates_ok
    if expect is not None:
        match = report.digests["combined"] == expect
        lines.append(f"  [{'PASS' if match else 'FAIL'}] expected digest: "
                     f"{'match' if match else 'MISMATCH'}")
        ok = ok and match
    lines.append("RESULT: " + ("OK" if ok else "GATE FAILURES — this does "
                               "not decode like an official MANO asset"))
    return "\n".join(lines), 0 if ok else 1


def report_json(report: VerifyReport, expect: Optional[str] = None) -> str:
    out = {
        "side": report.side,
        "gates_ok": report.gates_ok,
        "findings": [dataclasses.asdict(f) for f in report.findings],
        "digests": report.digests,
    }
    if expect is not None:
        out["expected_digest"] = expect
        out["expected_digest_match"] = (
            report.digests["combined"] == expect)
    return json.dumps(out, indent=2)
