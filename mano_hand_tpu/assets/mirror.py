"""Derive the opposite-side MANO asset by mirroring across the x=0 plane.

The official release ships left and right as two separate license-gated
files (/root/reference/dump_model.py:48-49), and the reference's only
notion of their relation is the scan extractor's axis-angle mirror
(`* [1, -1, -1]`, dump_model.py:38). This module makes the relation a
first-class operation on the asset itself: given ONE side, produce a
geometrically consistent opposite-side model.

Math (reflection M = diag(-1, 1, 1), M = M^-1):

- points mirror as ``x' = M x`` (template, shape blendshapes' offsets);
- rotations conjugate: ``R' = M R M``, which on axis-angle is exactly
  the reference's ``[1, -1, -1]`` component flip (axes are
  pseudo-vectors), and on the pose-corrective COEFFICIENTS
  ``(R - I)_ab`` is a sign ``s_a s_b`` per matrix entry — so the pose
  basis re-signs as ``basis'[v, c, (j,a,b)] = s_c s_a s_b
  basis[v, c, (j,a,b)]``;
- PCA statistics live in flat axis-angle space: mean and component rows
  multiply by the tiled ``[1, -1, -1]``;
- triangle winding reverses so outward orientation survives the
  reflection; regressor/skinning weights are per-vertex scalars and
  carry over unchanged.

The defining invariant (pinned by tests, exact in f64):
``forward(mirror(params), mirror_pose(pose), shape).verts ==
M @ forward(params, pose, shape).verts``.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from mano_hand_tpu import constants as C
from mano_hand_tpu.assets.schema import ManoParams, validate

# Pose/vertex mirroring for ARRAYS lives in assets.scans (mirror_pose,
# mirror_verts — the reference's dump_model.py:38 semantics); this module
# mirrors the ASSET so those relations hold between the two sides.


def mirror_params(params: ManoParams) -> ManoParams:
    """The opposite-side asset (see module docstring for the math)."""
    from mano_hand_tpu.assets.scans import MIRROR_AA, mirror_verts

    s = -MIRROR_AA                 # x=0 reflection signs = [-1, 1, 1]

    v_template = mirror_verts(params.v_template)
    shape_basis = np.asarray(params.shape_basis) * s[None, :, None]

    pb = np.asarray(params.pose_basis)         # [V, 3, (J-1)*9]
    v, _, p = pb.shape
    # Coefficient signs: s_a s_b per (a, b) rotation-matrix entry,
    # repeated per joint; output signs: s_c per vertex coordinate.
    ab = np.outer(s, s).reshape(9)             # [9] = s_a s_b, ab-major
    coeff_sign = np.tile(ab, p // 9)           # [(J-1)*9]
    pose_basis = pb * s[None, :, None] * coeff_sign[None, None, :]

    n_aa = np.asarray(params.pca_mean).shape[-1]
    aa_sign = np.tile(MIRROR_AA, n_aa // 3)
    pca_basis = np.asarray(params.pca_basis) * aa_sign[None, :]
    pca_mean = np.asarray(params.pca_mean) * aa_sign

    faces = np.asarray(params.faces)[:, ::-1].copy()   # re-orient winding

    dtype = np.asarray(params.v_template).dtype
    side = (C.NEUTRAL if params.side == C.NEUTRAL
            else C.LEFT if params.side == C.RIGHT else C.RIGHT)
    return validate(dataclasses.replace(
        params,
        v_template=v_template.astype(dtype),
        shape_basis=shape_basis.astype(dtype),
        pose_basis=pose_basis.astype(dtype),
        pca_basis=pca_basis.astype(dtype),
        pca_mean=pca_mean.astype(dtype),
        faces=faces,
        side=side,
    ))
