"""Scan-pose extraction from official MANO pickles (C9 parity).

Reproduces the reference's dump_scans (/root/reference/dump_model.py:24-43):
decode the per-scan PCA coefficients shipped inside the official pickles
(``hands_coeffs @ hands_components + hands_mean``), mirror the right-hand
poses into the left-hand frame by flipping the y/z axis-angle components
(dump_model.py:38), concatenate, and save as ``axangles.npy`` for the
animation path.
"""

from __future__ import annotations

import pickle
from pathlib import Path
from typing import Union

import numpy as np

from mano_hand_tpu.assets.loader import _dense

PathLike = Union[str, Path]

# Axis-angle mirror between left/right hands (dump_model.py:38): negate the
# y and z components of every rotation vector.
MIRROR_AA = np.array([1.0, -1.0, -1.0])


def mirror_pose(pose: np.ndarray) -> np.ndarray:
    """Mirror axis-angle pose(s) [..., 3] between left and right hands."""
    return np.asarray(pose) * MIRROR_AA


def mirror_verts(verts: np.ndarray) -> np.ndarray:
    """Mirror vertices [..., 3] across the x=0 plane (left<->right
    template relation)."""
    return np.asarray(verts) * np.array([-1.0, 1.0, 1.0])


def decode_scan_poses(official_pkl: PathLike) -> np.ndarray:
    """Scan poses [N, 15, 3] stored in one official MANO pickle."""
    with open(official_pkl, "rb") as f:
        raw = pickle.load(f, encoding="latin1")
    coeffs = _dense(raw["hands_coeffs"])
    basis = _dense(raw["hands_components"])
    mean = _dense(raw["hands_mean"])
    flat = coeffs @ basis + mean
    return flat.reshape(-1, 15, 3)


def extract_scan_poses(
    left_pkl: PathLike, right_pkl: PathLike
) -> np.ndarray:
    """All scan poses in the left-hand frame: left as-is, right mirrored.

    Matches dump_scans' concatenation order (left block then right block,
    dump_model.py:40)."""
    left = decode_scan_poses(left_pkl)
    right = mirror_pose(decode_scan_poses(right_pkl))
    return np.concatenate([left, right], axis=0)


def save_scan_poses(
    left_pkl: PathLike, right_pkl: PathLike, out_path: PathLike = "axangles.npy"
) -> Path:
    """dump_scans parity: write the pooled pose bank as .npy."""
    out_path = Path(out_path)
    np.save(out_path, extract_scan_poses(left_pkl, right_pkl))
    return out_path
