"""Model-family constants for the MANO hand.

These pin the shape contract described in the reference's asset schema
(/root/reference/dump_model.py:8-18 written, /root/reference/mano_np.py:20-36
read): 16 joints, 778 vertices, 1538 faces, 10 shape coefficients, 45
axis-angle pose dims (15 articulated joints x 3), and 135 pose-corrective
blendshape dims (15 x 9 rotation-matrix deltas).
"""

N_VERTS = 778
N_JOINTS = 16
N_FACES = 1538
N_SHAPE = 10
N_POSE_JOINTS = N_JOINTS - 1          # articulated joints (wrist excluded)
N_POSE_AXISANGLE = N_POSE_JOINTS * 3  # 45: flattened finger axis-angles
N_POSE_BASIS = N_POSE_JOINTS * 9      # 135: (R - I) rotation-matrix deltas

# The MANO kinematic tree (root = wrist), topologically ordered so every
# parent index precedes its children. Root's parent is -1 (the reference
# stores None at /root/reference/dump_model.py:18 and never dereferences it,
# /root/reference/mano_np.py:98).
MANO_PARENTS = (-1, 0, 1, 2, 0, 4, 5, 0, 7, 8, 0, 10, 11, 0, 13, 14)

LEFT = "left"
RIGHT = "right"
# Body-family assets (SMPL et al.) are unsided; the tag keeps mirror/scan
# logic honest (mirroring a neutral asset keeps it neutral).
NEUTRAL = "neutral"

# The SMPL-H kinematic tree: 22 body joints (SMPL order), then 15
# left-hand joints rooted at the left wrist (20), then 15 right-hand
# joints at the right wrist (21). The widest tree in the SMPL family and
# the canonical non-level-aligned case for the full-fusion kernel's
# segmented layout (ops/pallas_forward.py:level_layout).
SMPLH_PARENTS = (
    -1, 0, 0, 0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 9, 9, 12, 13, 14, 16, 17,
    18, 19,
    20, 22, 23, 20, 25, 26, 20, 28, 29, 20, 31, 32, 20, 34, 35,
    21, 37, 38, 21, 40, 41, 21, 43, 44, 21, 46, 47, 21, 49, 50,
)

# ---------------------------------------------------------------- keypoints
# The MANO skeleton regresses 16 joints (no fingertips — the tips are mesh
# surface, not skeleton). Hand-pose datasets and detectors (FreiHAND,
# HO-3D, InterHand2.6M, OpenPose/MediaPipe) use a 21-keypoint set: the 16
# joints plus one fingertip per finger, taken as fixed vertices of the
# official 778-vertex mesh. The reference never needs this (it has no
# fitting, /root/reference/mano_np.py), but any fitting framework does.
#
# Two vertex-id conventions circulate in the torch ecosystem; both are
# provided so targets produced against either stack plug in directly.
# Order within each tuple: (thumb, index, middle, ring, pinky).
TIP_VERTEX_IDS = {
    "smplx": (744, 320, 443, 554, 671),    # smplx VertexJointSelector
    "manopth": (745, 317, 444, 556, 673),  # manopth ManoLayer tips
}

# MANO's 16 joints are ordered wrist, index(3), middle(3), pinky(3),
# ring(3), thumb(3) — the kinematic-tree order of MANO_PARENTS above. With
# the 5 tips appended (thumb..pinky, indices 16..20), this permutation
# re-orders the 21 keypoints into the OpenPose/FreiHAND convention
# (wrist, thumb CMC->tip, index MCP->tip, middle, ring, pinky):
# openpose[i] = mano21[MANO21_TO_OPENPOSE[i]].
MANO21_TO_OPENPOSE = (
    0,
    13, 14, 15, 16,   # thumb chain + tip
    1, 2, 3, 17,      # index
    4, 5, 6, 18,      # middle
    10, 11, 12, 19,   # ring
    7, 8, 9, 20,      # pinky
)
