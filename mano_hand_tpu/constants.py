"""Model-family constants for the MANO hand.

These pin the shape contract described in the reference's asset schema
(/root/reference/dump_model.py:8-18 written, /root/reference/mano_np.py:20-36
read): 16 joints, 778 vertices, 1538 faces, 10 shape coefficients, 45
axis-angle pose dims (15 articulated joints x 3), and 135 pose-corrective
blendshape dims (15 x 9 rotation-matrix deltas).
"""

N_VERTS = 778
N_JOINTS = 16
N_FACES = 1538
N_SHAPE = 10
N_POSE_JOINTS = N_JOINTS - 1          # articulated joints (wrist excluded)
N_POSE_AXISANGLE = N_POSE_JOINTS * 3  # 45: flattened finger axis-angles
N_POSE_BASIS = N_POSE_JOINTS * 9      # 135: (R - I) rotation-matrix deltas

# The MANO kinematic tree (root = wrist), topologically ordered so every
# parent index precedes its children. Root's parent is -1 (the reference
# stores None at /root/reference/dump_model.py:18 and never dereferences it,
# /root/reference/mano_np.py:98).
MANO_PARENTS = (-1, 0, 1, 2, 0, 4, 5, 0, 7, 8, 0, 10, 11, 0, 13, 14)

LEFT = "left"
RIGHT = "right"
