"""Differentiable soft silhouette rasterizer (SoftRas-style aggregation).

The hard z-buffer renderer (viz/render.py) answers "what does the mesh
look like"; this module answers the INVERSE question — its output is a
smooth function of the vertices, so binary segmentation masks become a
fitting signal (``fitting.fit(data_term="silhouette")``). The reference
has no image-based fitting at all (its only image path is the OpenGL
viewer, /root/reference/data_explore.py:17-18); silhouette supervision is
how mesh models are fitted to the mask output of modern segmenters when
no keypoint detector is trusted.

Formulation (Liu et al., "Soft Rasterizer", ICCV 2019 — silhouette
channel only, no depth aggregation needed): every face contributes a
per-pixel occupancy

    occ_f(p) = sigmoid(d_signed(p, f) / sigma)

where ``d_signed`` is the screen-space distance (in PIXELS) from the
pixel center to the projected triangle's boundary, positive inside,
negative outside — continuous across the edge, so gradients push
triangles toward uncovered target pixels from several ``sigma`` away.
Faces combine by the probabilistic union

    sil(p) = 1 - prod_f (1 - occ_f(p))

evaluated as ``1 - exp(sum log1p(-occ))`` so the product over 1538 faces
neither underflows nor re-orders under XLA. No z-buffer, no culling:
occlusion does not change a silhouette.

TPU shape: the [P, F] pixel x face slabs are chunked by pixel rows with
``lax.map`` exactly like the hard rasterizer, every chunk dense vector
math (3 point-segment distances + a barycentric inside test per pair).
Batch/clip axes vmap on the outside.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from mano_hand_tpu.viz.camera import Camera, default_hand_camera
from mano_hand_tpu.viz.render import (
    best_chunk_rows, chunked_pixel_grid, ndc_to_pixels,
)

# Occupancies are clamped below 1 so log1p(-occ) and its gradient stay
# finite when sigmoid saturates deep inside the mesh.
_OCC_MAX = 1.0 - 1e-6


def _point_segment_sq(px, py, ax, ay, bx, by):
    """Squared distance from pixels [P] to segments [F] -> [P, F]."""
    abx, aby = bx - ax, by - ay                          # [F]
    apx = px[:, None] - ax[None, :]                      # [P, F]
    apy = py[:, None] - ay[None, :]
    denom = jnp.maximum(abx * abx + aby * aby, 1e-12)    # [F]
    t = jnp.clip(
        (apx * abx[None, :] + apy * aby[None, :]) / denom[None, :], 0.0, 1.0
    )
    dx = apx - t * abx[None, :]
    dy = apy - t * aby[None, :]
    return dx * dx + dy * dy


def _signed_dists(px, py, corners):
    """THE shared screen-space geometry of the soft rasterizers.

    px/py: [P] pixel centers; corners: [F, 3, 2] screen xy. Returns
    (signed [P, F] pixel distance to each triangle's boundary, positive
    inside; barycentrics l0/l1/l2 [P, F]). One implementation for the
    silhouette and depth chunks so the degenerate-face epsilon and edge
    handling cannot diverge.
    """
    ax, ay = corners[:, 0, 0], corners[:, 0, 1]
    bx, by = corners[:, 1, 0], corners[:, 1, 1]
    cx, cy = corners[:, 2, 0], corners[:, 2, 1]
    # Barycentric inside test — same expressions as the hard rasterizer's
    # coverage test, so the soft silhouette's 0.5 level set matches the
    # hard hit mask up to the sigma blur.
    d = (by - cy) * (ax - cx) + (cx - bx) * (ay - cy)    # [F] twice area
    safe_d = jnp.where(jnp.abs(d) < 1e-12, 1.0, d)
    pxc = px[:, None] - cx[None, :]
    pyc = py[:, None] - cy[None, :]
    l0 = ((by - cy)[None, :] * pxc + (cx - bx)[None, :] * pyc) / safe_d
    l1 = ((cy - ay)[None, :] * pxc + (ax - cx)[None, :] * pyc) / safe_d
    l2 = 1.0 - l0 - l1
    inside = (
        (l0 >= 0) & (l1 >= 0) & (l2 >= 0) & (jnp.abs(d)[None, :] > 1e-12)
    )
    # Distance to the triangle BOUNDARY = min over the three edges; the
    # +1e-12 keeps the sqrt's gradient finite for pixels exactly on an
    # edge (where the true distance is 0 and the sign flips — the value
    # is continuous there, which is all the sigmoid needs).
    e2 = jnp.minimum(
        jnp.minimum(
            _point_segment_sq(px, py, ax, ay, bx, by),
            _point_segment_sq(px, py, bx, by, cx, cy),
        ),
        _point_segment_sq(px, py, cx, cy, ax, ay),
    )
    dist = jnp.sqrt(e2 + 1e-12)                          # [P, F] pixels
    return jnp.where(inside, dist, -dist), l0, l1, l2


def _sil_chunk(px, py, corners, sigma):
    """Soft coverage of a pixel chunk against every face: [P] in [0, 1]."""
    signed, _, _, _ = _signed_dists(px, py, corners)
    occ = jnp.minimum(jax.nn.sigmoid(signed / sigma), _OCC_MAX)
    return 1.0 - jnp.exp(jnp.sum(jnp.log1p(-occ), axis=1))


@functools.partial(
    jax.jit, static_argnames=("height", "width", "chunk_rows")
)
def _sil_impl(verts, faces, camera, sigma,
              height: int, width: int, chunk_rows: int):
    proj = camera.project(verts)                         # [V, 3]
    # render.py's shared NDC -> pixel mapping: masks painted against
    # rendered images line up pixel-for-pixel by construction.
    corners = ndc_to_pixels(proj[:, :2], height, width)[faces]  # [F, 3, 2]
    gx, gy = chunked_pixel_grid(height, width, chunk_rows, verts.dtype)
    sil = jax.lax.map(
        lambda pix: _sil_chunk(pix[0], pix[1], corners, sigma), (gx, gy)
    )
    return sil.reshape(height, width)


def _depth_chunk(px, py, corners, depths, sigma, gamma, z_background):
    """Soft depth of a pixel chunk.

    Two decisions, factored so neither can swamp the other: COVERAGE
    (the silhouette's probabilistic union) decides foreground vs
    background — a softmin with the background in the pool would let
    any face's meters-scale z advantage (e^(Δz/gamma)) overwhelm its
    vanishing occupancy far outside the mesh and paint the whole image
    foreground. WHICH face is then a coverage-weighted softmin over z
    with temperature ``gamma`` (the soft z-buffer: the nearest covering
    face dominates), in log space with max-subtraction so meters-scale
    z never overflows the exp. Barycentric z is clamped+renormalized so
    near-edge pixels read the face's edge depth instead of
    extrapolating.
    """
    signed, l0, l1, l2 = _signed_dists(px, py, corners)
    occ = jnp.minimum(jax.nn.sigmoid(signed / sigma), _OCC_MAX)
    lc0, lc1, lc2 = (jnp.clip(l, 0.0, 1.0) for l in (l0, l1, l2))
    norm = jnp.maximum(lc0 + lc1 + lc2, 1e-12)
    z = (lc0 * depths[None, :, 0] + lc1 * depths[None, :, 1]
         + lc2 * depths[None, :, 2]) / norm                 # [P, F]
    sil = 1.0 - jnp.exp(jnp.sum(jnp.log1p(-occ), axis=1))   # coverage
    # log_sigmoid keeps the coverage penalty UNBOUNDED (decays ~ -d/sigma
    # forever): a log(occ + eps) floor at ~-27.6 would let any face
    # >~27.6*gamma nearer steal the softmin from the truly covering face
    # 20 px away — a 20 cm depth error inside the silhouette.
    logw = jax.nn.log_sigmoid(signed / sigma) - z / gamma   # faces only
    m = jnp.max(logw, axis=1)                               # [P]
    w = jnp.exp(logw - m[:, None])
    depth_faces = (w * z).sum(axis=1) / jnp.maximum(
        w.sum(axis=1), 1e-12
    )
    return sil * depth_faces + (1.0 - sil) * z_background


@functools.partial(
    jax.jit, static_argnames=("height", "width", "chunk_rows")
)
def _depth_impl(verts, faces, camera, sigma, gamma, z_background,
                height: int, width: int, chunk_rows: int):
    proj = camera.project(verts)
    corners = ndc_to_pixels(proj[:, :2], height, width)[faces]
    depths = proj[:, 2][faces]                              # view-space z
    gx, gy = chunked_pixel_grid(height, width, chunk_rows, verts.dtype)
    depth = jax.lax.map(
        lambda pix: _depth_chunk(pix[0], pix[1], corners, depths, sigma,
                                 gamma, z_background), (gx, gy)
    )
    return depth.reshape(height, width)


def soft_depth(
    verts: jnp.ndarray,              # [V, 3] or [..., V, 3]
    faces: jnp.ndarray,              # [F, 3] int
    camera: Optional[Camera] = None,
    height: int = 64,
    width: int = 64,
    sigma: float = 0.7,
    gamma: float = 0.005,
    z_background: float = 10.0,
    chunk_rows: int = 8,
    batch_mode: str = "auto",        # "auto" | "vmap" | "map"
) -> jnp.ndarray:
    """Soft depth image(s) in view-space meters: [..., H, W].

    The differentiable z-buffer completing the render triple
    (shaded / silhouette / depth): pixels covered by the mesh read the
    softmin (temperature ``gamma``, meters) of the covering faces'
    interpolated z — the front surface, which is what a depth sensor
    sees — and uncovered pixels read ``z_background``. Unlike the
    silhouette, depth observes the axis a single outline cannot: one
    depth image pins full 3D translation
    (``fitting.fit(data_term="depth")``). ``gamma`` trades occlusion
    crispness against gradient flow to back faces; the default 5 mm is
    far below hand-to-camera distances and above f32 noise.
    """
    if camera is None:
        camera = default_hand_camera()
    for name, val in (("sigma", sigma), ("gamma", gamma)):
        if not isinstance(val, jax.core.Tracer) and float(val) <= 0:
            raise ValueError(f"{name} must be > 0, got {val}")
    chunk_rows = best_chunk_rows(height, chunk_rows)
    verts = jnp.asarray(verts)
    faces = jnp.asarray(faces, jnp.int32)
    dt = verts.dtype
    render = lambda v: _depth_impl(                      # noqa: E731
        v, faces, camera, jnp.asarray(sigma, dt), jnp.asarray(gamma, dt),
        jnp.asarray(z_background, dt), height, width, chunk_rows,
    )
    return _render_batched(render, verts, faces.shape[0], width,
                           chunk_rows, height, batch_mode)


# The auto batch policy's budget for one [B, chunk_pixels, F] distance
# slab (x ~6 live temporaries inside the chunk body): vmap the whole
# batch when it fits, fall back to one-image-at-a-time lax.map beyond.
_VMAP_SLAB_BYTES = 64 * 1024 * 1024


def _render_batched(render, verts, n_faces, width, chunk_rows,
                    height, batch_mode):
    """THE batch dispatch shared by the soft renderers.

    Small batches VMAP into one dense program (B sequential launches
    under-fill an accelerator's vector units at mask-fitting sizes; CPU
    measured ~11% faster under map, so it always maps), large ones fall
    back to one-image-at-a-time lax.map so the [B, chunk_pixels, F]
    slabs stay bounded.
    """
    if batch_mode not in ("auto", "vmap", "map"):
        raise ValueError(
            f"batch_mode must be 'auto', 'vmap' or 'map', got {batch_mode!r}"
        )
    if verts.ndim == 2:
        return render(verts)
    lead = verts.shape[:-2]
    flat = verts.reshape((-1,) + verts.shape[-2:])
    if batch_mode == "auto":
        slab = (flat.shape[0] * chunk_rows * width * n_faces
                * flat.dtype.itemsize)
        batch_mode = (
            "vmap" if slab <= _VMAP_SLAB_BYTES
            and jax.default_backend() != "cpu" else "map"
        )
    batched = jax.vmap(render) if batch_mode == "vmap" else (
        lambda x: jax.lax.map(render, x)
    )
    return batched(flat).reshape(lead + (height, width))


def soft_silhouette(
    verts: jnp.ndarray,              # [V, 3] or [..., V, 3]
    faces: jnp.ndarray,              # [F, 3] int
    camera: Optional[Camera] = None,
    height: int = 64,
    width: int = 64,
    sigma: float = 0.7,
    chunk_rows: int = 8,
    batch_mode: str = "auto",        # "auto" | "vmap" | "map"
) -> jnp.ndarray:
    """Soft occupancy image(s) in [0, 1]: [..., H, W].

    ``sigma`` is the edge softness in PIXELS (occupancy crosses 0.5 at
    the triangle boundary and saturates ~3 sigma away on either side).
    Small sigma = crisp mask but short-range gradients; large sigma =
    blurrier mask whose gradients reach pixels further from the current
    silhouette — anneal it downward for hard fitting problems.

    Leading batch/frame axes: small batches VMAP (the whole batch's
    pixel×face tests become one dense program — on an accelerator,
    sequential per-image launches leave the vector units mostly idle at
    mask-fitting sizes), large ones fall back to one-image-at-a-time
    ``lax.map`` so the [B, pixels, F] slabs stay bounded. ``batch_mode``
    pins either path ("auto" switches on a ~64 MB slab budget).
    """
    if camera is None:
        camera = default_hand_camera()
    if not isinstance(sigma, jax.core.Tracer) and float(sigma) <= 0:
        # sigma 0 divides by zero (NaN occupancy everywhere); negative
        # inverts inside/outside and the fit optimizes the complement.
        # Traced sigmas (jitted callers) pass through — their concrete
        # value was checked at the caller's jit boundary.
        raise ValueError(f"sigma must be > 0 pixels, got {sigma}")
    chunk_rows = best_chunk_rows(height, chunk_rows)
    verts = jnp.asarray(verts)
    faces = jnp.asarray(faces, jnp.int32)
    sigma = jnp.asarray(sigma, verts.dtype)
    render = lambda v: _sil_impl(                        # noqa: E731
        v, faces, camera, sigma, height, width, chunk_rows
    )
    return _render_batched(render, verts, faces.shape[0], width,
                           chunk_rows, height, batch_mode)
