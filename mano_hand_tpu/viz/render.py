"""Pure-JAX z-buffer triangle rasterizer with Gouraud shading.

Replaces the reference's OpenGL viewer dependency (vctoolkit TriMeshViewer,
/root/reference/data_explore.py:17-18) with a renderer that is itself a TPU
program: static shapes, no data-dependent control flow, brute-force
pixel x face coverage tests chunked by pixel rows (``lax.map``) so the
[P, F] barycentric intermediates stay small while every chunk is dense
vector math. A whole animation clip renders as one jitted/vmapped program.

Screen-space barycentric depth interpolation (not perspective-correct) —
exact at vertices and more than adequate for mesh inspection at MANO scale.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from mano_hand_tpu.ops import vertex_normals
from mano_hand_tpu.viz.camera import Camera, default_hand_camera

_BG = (1.0, 1.0, 1.0)
_BASE = (0.82, 0.68, 0.58)  # skin-ish albedo
_FAR = 1e30


def _shade(
    verts: jnp.ndarray, faces: jnp.ndarray, camera: Camera,
    light_dir: jnp.ndarray,
) -> jnp.ndarray:
    """Per-vertex Lambert intensity in [ambient, 1]."""
    normals = vertex_normals(verts, faces)
    light = light_dir / jnp.linalg.norm(light_dir)
    lambert = jnp.clip(-(normals @ light), 0.0, 1.0)
    return 0.35 + 0.65 * lambert


def ndc_to_pixels(proj_xy: jnp.ndarray, height: int, width: int):
    """NDC xy [..., 2] -> RASTER coords [..., 2], y flipped so +y in world
    points up on screen. THE raster-space mapping — the hard renderer and
    the soft silhouette both use it, which is what guarantees that masks
    fitted via ``soft_silhouette`` line up pixel-for-pixel with
    ``render_mesh`` output (pinned by a registration test).

    NOT the same mapping as ``IntrinsicsCamera.ndc_to_pixels``: raster
    coordinates put pixel u's center at u+0.5, whereas the camera method
    returns OpenCV pixel-center coordinates (center of pixel u at
    integer u, half a pixel lower). Keep renders in this space and
    dataset annotations in the camera's."""
    sx = (proj_xy[..., 0] * 0.5 + 0.5) * width
    sy = (1.0 - (proj_xy[..., 1] * 0.5 + 0.5)) * height
    return jnp.stack([sx, sy], axis=-1)


def chunked_pixel_grid(height: int, width: int, chunk_rows: int, dtype):
    """Pixel-center coordinates grouped into row chunks for ``lax.map``:
    (gx, gy), each [height // chunk_rows, chunk_rows * width]."""
    ys = jnp.arange(height, dtype=dtype) + 0.5
    xs = jnp.arange(width, dtype=dtype) + 0.5
    gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
    return (
        gx.reshape(height // chunk_rows, chunk_rows * width),
        gy.reshape(height // chunk_rows, chunk_rows * width),
    )


def best_chunk_rows(height: int, chunk_rows: int) -> int:
    """Largest divisor of ``height`` that is <= ``chunk_rows`` — keeps odd
    image heights (e.g. 100- or 180-row masks) from silently degrading to
    one-row chunks and multiplying the ``lax.map`` trip count."""
    return max(c for c in range(1, chunk_rows + 1) if height % c == 0)


def _raster_chunk(px, py, corners, depths, attrs):
    """Coverage test of a pixel chunk against every face.

    px/py: [P] pixel centers; corners: [F, 3, 2] screen xy;
    depths: [F, 3]; attrs: [F, 3, C] per-corner attribute channels.
    Returns (interpolated attrs [P, C], hit [P]).
    """
    ax, ay = corners[:, 0, 0], corners[:, 0, 1]
    bx, by = corners[:, 1, 0], corners[:, 1, 1]
    cx, cy = corners[:, 2, 0], corners[:, 2, 1]
    d = (by - cy) * (ax - cx) + (cx - bx) * (ay - cy)          # [F]
    safe_d = jnp.where(jnp.abs(d) < 1e-12, 1.0, d)
    pxc = px[:, None] - cx[None, :]                             # [P, F]
    pyc = py[:, None] - cy[None, :]
    l0 = ((by - cy)[None, :] * pxc + (cx - bx)[None, :] * pyc) / safe_d
    l1 = ((cy - ay)[None, :] * pxc + (ax - cx)[None, :] * pyc) / safe_d
    l2 = 1.0 - l0 - l1
    inside = (
        (l0 >= 0) & (l1 >= 0) & (l2 >= 0) & (jnp.abs(d)[None, :] > 1e-12)
    )
    z = (
        l0 * depths[None, :, 0]
        + l1 * depths[None, :, 1]
        + l2 * depths[None, :, 2]
    )
    inside = inside & (z > 0)
    z = jnp.where(inside, z, _FAR)
    best = jnp.argmin(z, axis=1)                                # [P]
    hit = jnp.take_along_axis(inside, best[:, None], axis=1)[:, 0]
    lam = jnp.stack(
        [
            jnp.take_along_axis(l, best[:, None], axis=1)[:, 0]
            for l in (l0, l1, l2)
        ],
        axis=-1,
    )                                                           # [P, 3]
    vals = (attrs[best] * lam[:, :, None]).sum(1)               # [P, C]
    return vals, hit


@functools.partial(
    jax.jit, static_argnames=("height", "width", "chunk_rows")
)
def _render_impl(
    verts, faces, camera, light_dir, base_color, bg_color,
    height: int, width: int, chunk_rows: int,
    vertex_colors=None,
):
    proj = camera.project(verts)                                # [V, 3]
    screen = ndc_to_pixels(proj[:, :2], height, width)          # [V, 2]
    corners = screen[faces]                                     # [F, 3, 2]
    depths = proj[:, 2][faces]                                  # [F, 3]
    intens = _shade(verts, faces, camera, light_dir)[faces]     # [F, 3]
    if vertex_colors is None:
        attrs = intens[:, :, None]                              # [F, 3, 1]
    else:
        # Gouraud per-vertex colors, still Lambert-shaded so geometry
        # reads under the heatmap/albedo.
        attrs = vertex_colors[faces] * intens[:, :, None]       # [F, 3, 3]

    gx, gy = chunked_pixel_grid(height, width, chunk_rows, jnp.float32)

    def row_chunk(pix):
        px, py = pix
        return _raster_chunk(px, py, corners, depths, attrs)

    vals, hit = jax.lax.map(row_chunk, (gx, gy))                # chunked
    vals = vals.reshape(height, width, -1)
    hit = hit.reshape(height, width, 1)
    if vertex_colors is None:
        rgb = vals * base_color[None, None, :]
    else:
        rgb = vals
    return jnp.where(hit, rgb, bg_color[None, None, :])


def render_mesh(
    verts,
    faces,
    camera: Optional[Camera] = None,
    height: int = 256,
    width: int = 256,
    light_dir: Sequence[float] = (0.3, -0.4, 1.0),
    base_color: Sequence[float] = _BASE,
    bg_color: Sequence[float] = _BG,
    chunk_rows: int = 16,
    vertex_colors=None,            # [V, 3] per-vertex RGB (Gouraud)
) -> jnp.ndarray:
    """Render one mesh to an [H, W, 3] float image in [0, 1].

    ``vertex_colors`` swaps the uniform albedo for per-vertex RGB,
    barycentrically interpolated and Lambert-shaded — the fit-diagnostic
    path: map per-vertex errors through ``error_colormap`` and SEE where
    a registration is off instead of reading a scalar loss.
    """
    if camera is None:
        camera = default_hand_camera()
    chunk_rows = best_chunk_rows(height, chunk_rows)
    if vertex_colors is not None:
        vertex_colors = jnp.asarray(vertex_colors, jnp.float32)
        # np.shape reads metadata only — no device-to-host transfer.
        if vertex_colors.shape != (np.shape(verts)[-2], 3):
            raise ValueError(
                f"vertex_colors must be [V, 3] matching verts, got "
                f"{vertex_colors.shape}"
            )
    return _render_impl(
        jnp.asarray(verts, jnp.float32),
        jnp.asarray(faces, jnp.int32),
        camera,
        jnp.asarray(light_dir, jnp.float32),
        jnp.asarray(base_color, jnp.float32),
        jnp.asarray(bg_color, jnp.float32),
        height, width, chunk_rows,
        vertex_colors=vertex_colors,
    )


def error_colormap(
    values,                        # [V] per-vertex scalars (e.g. meters)
    vmax: Optional[float] = None,  # None = max of values
) -> jnp.ndarray:
    """Map per-vertex scalars to a blue→white→red ramp ([V, 3] RGB).

    The registration-error convention: 0 = cool blue, midscale = white,
    ``vmax`` (default the max) = red. Pass the result as ``render_mesh``'s
    ``vertex_colors`` to see WHERE a fit deviates — e.g.
    ``error_colormap(jnp.linalg.norm(fit_verts - target_verts, axis=-1))``.
    """
    v = jnp.asarray(values, jnp.float32)
    # Both branches guard /0: an explicit vmax=0 (e.g. a shared scale
    # derived from a perfect fit) must yield all-blue, not all-NaN.
    top = jnp.maximum(
        jnp.asarray(vmax, jnp.float32) if vmax is not None else v.max(),
        1e-12,
    )
    t = jnp.clip(v / top, 0.0, 1.0)
    lo = jnp.asarray([0.23, 0.30, 0.75], jnp.float32)   # cool blue
    mid = jnp.asarray([0.96, 0.96, 0.96], jnp.float32)  # white
    hi = jnp.asarray([0.71, 0.02, 0.15], jnp.float32)   # red
    s = t[:, None]
    return jnp.where(
        s < 0.5,
        lo + (mid - lo) * (2.0 * s),
        mid + (hi - mid) * (2.0 * s - 1.0),
    )


def render_sequence(
    verts_seq,                       # [T, V, 3]
    faces,
    camera: Optional[Camera] = None,
    height: int = 256,
    width: int = 256,
    **kwargs,
) -> np.ndarray:
    """Render an animation clip to [T, H, W, 3]; frames vmap on-device."""
    if camera is None:
        camera = default_hand_camera()
    render = lambda v: render_mesh(
        v, faces, camera, height=height, width=width, **kwargs
    )
    # lax.map bounds memory for long clips; each frame is already chunked.
    return np.asarray(
        jax.lax.map(render, jnp.asarray(verts_seq, jnp.float32))
    )
