"""Camera model for the software rasterizer.

The reference's demo applies a hand-built view rotation before rendering
(/root/reference/data_explore.py:10,15 — a transforms3d axis-angle matrix).
``view_rotation`` reproduces that role natively (via the same safe
Rodrigues kernel the model uses); ``look_at`` + ``Camera`` give a proper
pinhole projection for stills and turntables.
"""

from __future__ import annotations

from typing import NamedTuple, Sequence

import jax.numpy as jnp
import numpy as np

from mano_hand_tpu.ops.common import EPS
from mano_hand_tpu.ops.rodrigues import rotation_matrix


class Camera(NamedTuple):
    """Pinhole camera: world -> view rotation R, translation t, focal.

    ``project(v) = focal * (R @ v + t).xy / (R @ v + t).z`` in NDC units;
    z after transform must be positive (camera looks down +z).
    """

    rot: jnp.ndarray     # [3, 3]
    trans: jnp.ndarray   # [3]
    focal: float = 1.0

    def transform(self, verts: jnp.ndarray) -> jnp.ndarray:
        """World verts [..., 3] -> view space [..., 3]."""
        return verts @ self.rot.T + self.trans

    def project(self, verts: jnp.ndarray) -> jnp.ndarray:
        """World verts [..., 3] -> (x_ndc, y_ndc, depth) [..., 3]."""
        v = self.transform(verts)
        z = jnp.maximum(v[..., 2:3], EPS)
        xy = self.focal * v[..., :2] / z
        return jnp.concatenate([xy, v[..., 2:3]], axis=-1)


class WeakPerspectiveCamera(NamedTuple):
    """Weak-perspective (scaled-orthographic) camera.

    ``project(v) = scale * (R @ v).xy + trans2d`` — the (s, tx, ty)
    convention HMR-family regressors and many hand datasets annotate with:
    no depth division, so image position is linear in the joints. Use as
    the ``camera=`` of ``fitting.fit(data_term="keypoints2d")``
    interchangeably with the pinhole ``Camera`` (both expose
    ``project``); prefer it when the hand's depth extent is small
    relative to its distance, or when the annotations were made under
    this model in the first place (fitting a pinhole camera to
    weak-perspective annotations bakes the mismatch into the pose).
    The third output column is view-space depth, same as ``Camera`` —
    informational here, never part of the 2D residual.
    """

    rot: jnp.ndarray      # [3, 3]
    scale: float = 1.0
    trans2d: jnp.ndarray = None  # [2]; None = origin

    def transform(self, verts: jnp.ndarray) -> jnp.ndarray:
        """World verts [..., 3] -> view space [..., 3] (rotation only)."""
        return verts @ self.rot.T

    def project(self, verts: jnp.ndarray) -> jnp.ndarray:
        """World verts [..., 3] -> (x, y, depth) [..., 3]."""
        v = self.transform(verts)
        xy = self.scale * v[..., :2]
        if self.trans2d is not None:
            xy = xy + jnp.asarray(self.trans2d, v.dtype)
        return jnp.concatenate([xy, v[..., 2:3]], axis=-1)


class IntrinsicsCamera(NamedTuple):
    """Pinhole camera from a REAL calibration matrix (pixel units).

    Datasets annotate with K = [[fx, 0, cx], [0, fy, cy], [0, 0, 1]] and
    pixel keypoints; this camera exposes that convention on top of the
    package's NDC plumbing. ``project`` returns NDC such that the
    rasterizer's NDC→pixel mapping (render.ndc_to_pixels at this
    ``width``/``height``) lands each vertex on the raster sample of its
    intrinsic pixel (u, v) = (fx·X/Z + cx, fy·Y/Z + cy) — i.e. raster
    coordinate u + 0.5, the center of OpenCV pixel u — so renders, soft
    silhouettes, and mask fitting line up with the dataset's images
    pixel-for-pixel. Convert pixel-space detector keypoints once with
    ``pixels_to_ndc`` and fit as usual (residuals then live in NDC:
    2/width pixel units — scale `robust_scale` accordingly).
    """

    rot: jnp.ndarray     # [3, 3] world -> camera
    trans: jnp.ndarray   # [3]
    fx: float
    fy: float
    cx: float
    cy: float
    width: int
    height: int

    def transform(self, verts: jnp.ndarray) -> jnp.ndarray:
        """World verts [..., 3] -> view space [..., 3]."""
        return verts @ self.rot.T + self.trans

    def project(self, verts: jnp.ndarray) -> jnp.ndarray:
        """World verts [..., 3] -> (x_ndc, y_ndc, depth) [..., 3]."""
        v = self.transform(verts)
        z = jnp.maximum(v[..., 2:3], EPS)
        u = self.fx * v[..., 0:1] / z + self.cx
        w = self.fy * v[..., 1:2] / z + self.cy
        # ONE uv->NDC mapping (pixels_to_ndc) serves projection and
        # keypoint conversion — they must match by contract.
        xy = self.pixels_to_ndc(jnp.concatenate([u, w], axis=-1))
        return jnp.concatenate([xy, v[..., 2:3]], axis=-1)

    def pixels_to_ndc(self, uv: jnp.ndarray) -> jnp.ndarray:
        """OpenCV-convention pixel coords [..., 2] (u right, v down,
        origin top-left, integer values at pixel CENTERS — the K-matrix
        convention) -> the NDC space ``project`` emits. Run detector
        annotations through this once, then fit(data_term='keypoints2d').

        The +0.5 shifts between conventions: the rasterizer samples
        pixel i at continuous coordinate i+0.5, so intrinsic coordinate
        u lands on raster coordinate u+0.5 — without it every render
        and mask would sit half a pixel off the dataset image.
        """
        uv = jnp.asarray(uv)
        return jnp.stack(
            [2.0 * (uv[..., 0] + 0.5) / self.width - 1.0,
             1.0 - 2.0 * (uv[..., 1] + 0.5) / self.height],
            axis=-1,
        )

    def ndc_to_pixels(self, xy: jnp.ndarray) -> jnp.ndarray:
        """Inverse of ``pixels_to_ndc`` (e.g. to draw fitted joints on
        the dataset image, OpenCV convention).

        NOT the same mapping as ``viz.render.ndc_to_pixels``: this one
        returns OpenCV pixel-CENTER coordinates (integer u lands on the
        center of pixel u, hence the -0.5), while the render helper
        returns raster coordinates where pixel u's center sits at u+0.5.
        Use this for dataset/annotation space, the render one for
        indexing into rendered images; mixing them shifts everything by
        half a pixel."""
        xy = jnp.asarray(xy)
        return jnp.stack(
            [(xy[..., 0] + 1.0) * 0.5 * self.width - 0.5,
             (1.0 - xy[..., 1]) * 0.5 * self.height - 0.5],
            axis=-1,
        )


def from_intrinsics(
    k_matrix,                      # [3, 3] calibration matrix K
    width: int,
    height: int,
    rot=None,                      # [3, 3] world->camera; default identity
    trans=(0.0, 0.0, 0.5),         # [3]; hands need positive view z
) -> IntrinsicsCamera:
    """Build an ``IntrinsicsCamera`` from a dataset's K matrix."""
    k = np.asarray(k_matrix, np.float64)
    if k.shape != (3, 3):
        raise ValueError(f"K must be [3, 3], got {k.shape}")
    if k[0, 0] <= 0 or k[1, 1] <= 0:
        raise ValueError(f"fx/fy must be > 0, got {k[0, 0]}, {k[1, 1]}")
    if abs(k[0, 1]) > 1e-6:
        # Silently dropping the skew term would bias every projected u
        # by skew*Y/Z pixels; refuse the unsupported calibration.
        raise ValueError(
            f"skewed calibrations (K[0,1]={k[0, 1]:g}) are not supported"
        )
    if width <= 0 or height <= 0:
        # pixels_to_ndc divides by these; zero would make every NDC
        # target inf and the fit would "succeed" on NaNs.
        raise ValueError(
            f"width/height must be > 0, got {width}x{height}"
        )
    return IntrinsicsCamera(
        rot=jnp.asarray(
            np.eye(3) if rot is None else np.asarray(rot), jnp.float32
        ),
        trans=jnp.asarray(trans, jnp.float32),
        fx=float(k[0, 0]), fy=float(k[1, 1]),
        cx=float(k[0, 2]), cy=float(k[1, 2]),
        width=int(width), height=int(height),
    )


def view_rotation(axis_angle: Sequence[float]) -> jnp.ndarray:
    """Axis-angle view matrix, the rasterizer-side analogue of the demo's
    transforms3d usage. Accepts a length-3 vector; angle = norm."""
    aa = jnp.asarray(axis_angle, jnp.float32).reshape(3)
    return rotation_matrix(aa.reshape(1, 3))[0]


def look_at(
    eye: Sequence[float],
    target: Sequence[float] = (0.0, 0.0, 0.0),
    up: Sequence[float] = (0.0, 1.0, 0.0),
    focal: float = 1.2,
) -> Camera:
    """Camera at ``eye`` looking at ``target`` (numpy-side construction)."""
    eye = np.asarray(eye, np.float64)
    fwd = np.asarray(target, np.float64) - eye
    fwd = fwd / max(np.linalg.norm(fwd), EPS)
    right = np.cross(np.asarray(up, np.float64), fwd)
    right = right / max(np.linalg.norm(right), EPS)
    cam_up = np.cross(fwd, right)  # right-handed: right x up = fwd
    rot = np.stack([right, cam_up, fwd])        # rows = camera axes, y = up
    trans = -rot @ eye
    return Camera(
        rot=jnp.asarray(rot, jnp.float32),
        trans=jnp.asarray(trans, jnp.float32),
        focal=float(focal),
    )


def default_hand_camera(scale: float = 0.25) -> Camera:
    """A framing that keeps a MANO hand (~0.2 m span near the origin) in
    view: straight-on, slightly pulled back along -z."""
    return look_at(eye=(0.0, 0.0, -3.0 * scale), focal=2.2)
