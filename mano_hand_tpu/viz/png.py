"""Minimal dependency-free image writers (PNG, animated GIF).

The reference renders AVI via its external viewer (vctoolkit,
/root/reference/data_explore.py:17); shipping codecs is out of scope for a
model framework, but PNG (zlib is in the stdlib) and GIF89a (self-contained
LZW below) cover stills and animation previews with zero dependencies.
"""

from __future__ import annotations

import struct
import zlib
from pathlib import Path
from typing import Sequence, Union

import numpy as np

PathLike = Union[str, Path]


def _to_u8(image: np.ndarray) -> np.ndarray:
    image = np.asarray(image)
    if image.dtype != np.uint8:
        image = (np.clip(image, 0.0, 1.0) * 255.0 + 0.5).astype(np.uint8)
    if image.ndim == 2:
        image = image[..., None].repeat(3, axis=-1)
    return image


def write_png(image: np.ndarray, path: PathLike) -> Path:
    """Write [H, W, 3] (float in [0,1] or uint8) as an RGB PNG."""
    image = _to_u8(image)
    h, w = image.shape[:2]
    raw = b"".join(
        b"\x00" + image[y].tobytes() for y in range(h)  # filter 0 per row
    )

    def chunk(tag: bytes, payload: bytes) -> bytes:
        return (
            struct.pack(">I", len(payload)) + tag + payload
            + struct.pack(">I", zlib.crc32(tag + payload) & 0xFFFFFFFF)
        )

    ihdr = struct.pack(">IIBBBBB", w, h, 8, 2, 0, 0, 0)  # 8-bit RGB
    data = (
        b"\x89PNG\r\n\x1a\n"
        + chunk(b"IHDR", ihdr)
        + chunk(b"IDAT", zlib.compress(raw, 6))
        + chunk(b"IEND", b"")
    )
    path = Path(path)
    path.write_bytes(data)
    return path


def _lzw_encode(indices: np.ndarray, code_bits: int) -> bytes:
    """GIF-flavor LZW: variable-width codes, clear/end markers."""
    clear = 1 << code_bits
    end = clear + 1
    table = {bytes([i]): i for i in range(clear)}
    next_code = end + 1
    width = code_bits + 1

    out_bits: list = []
    acc = 0
    nacc = 0

    def emit(code: int, w: int) -> None:
        nonlocal acc, nacc
        acc |= code << nacc
        nacc += w
        while nacc >= 8:
            out_bits.append(acc & 0xFF)
            acc >>= 8
            nacc -= 8

    emit(clear, width)
    prefix = b""
    for sym in indices.tobytes():
        trial = prefix + bytes([sym])
        if trial in table:
            prefix = trial
            continue
        emit(table[prefix], width)
        table[trial] = next_code
        next_code += 1
        if next_code > (1 << width) and width < 12:
            width += 1
        elif next_code >= 4096:
            emit(clear, width)
            table = {bytes([i]): i for i in range(clear)}
            next_code = end + 1
            width = code_bits + 1
        prefix = bytes([sym])
    if prefix:
        emit(table[prefix], width)
    emit(end, width)
    if nacc:
        out_bits.append(acc & 0xFF)
    return bytes(out_bits)


def write_gif(
    frames: Union[np.ndarray, Sequence[np.ndarray]],
    path: PathLike,
    fps: int = 20,
    levels: int = 64,
) -> Path:
    """Write [T, H, W, 3] frames as a looping grayscale-quantized GIF89a.

    Each frame is luma-quantized to ``levels`` gray entries — ample for
    shaded-mesh previews and keeps the encoder tiny and deterministic.
    """
    frames = [_to_u8(f) for f in frames]
    h, w = frames[0].shape[:2]
    delay_cs = max(2, round(100 / max(fps, 1)))

    # Global 256-entry grayscale palette (levels used, rest padded).
    pal = bytearray()
    for i in range(256):
        g = min(i, levels - 1) * 255 // (levels - 1)
        pal += bytes((g, g, g))

    out = bytearray()
    out += b"GIF89a"
    out += struct.pack("<HHBBB", w, h, 0xF7, 0, 0)  # global palette, 256
    out += bytes(pal)
    out += b"\x21\xFF\x0BNETSCAPE2.0\x03\x01\x00\x00\x00"  # loop forever
    for f in frames:
        luma = (
            0.299 * f[..., 0] + 0.587 * f[..., 1] + 0.114 * f[..., 2]
        )
        idx = np.clip(
            (luma / 255.0 * (levels - 1) + 0.5).astype(np.uint8),
            0, levels - 1,
        )
        out += b"\x21\xF9\x04\x04" + struct.pack("<H", delay_cs) + b"\x00\x00"
        out += b"\x2C" + struct.pack("<HHHH", 0, 0, w, h) + b"\x00"
        out += bytes([8])  # LZW min code size
        data = _lzw_encode(idx.reshape(-1), 8)
        for off in range(0, len(data), 255):
            block = data[off:off + 255]
            out += bytes([len(block)]) + block
        out += b"\x00"
    out += b"\x3B"
    path = Path(path)
    path.write_bytes(bytes(out))
    return path
