"""TPU-native visualization: a pure-JAX mesh rasterizer.

The reference's visualization (C11, /root/reference/data_explore.py:1-18)
depends on an external OpenGL viewer (vctoolkit + transforms3d) to render
scan-pose animations to AVI. This subsystem replaces that with a
dependency-free, jittable software renderer: camera transforms, a z-buffer
triangle rasterizer with Lambert shading, and a pure-Python PNG/GIF writer
— so `cli render` produces shaded hand images and animations on any host,
and whole animation clips render as one batched XLA program on TPU.
"""

from mano_hand_tpu.viz.camera import Camera, look_at, view_rotation
from mano_hand_tpu.viz.render import render_mesh, render_sequence
from mano_hand_tpu.viz.png import write_png, write_gif

__all__ = [
    "Camera",
    "look_at",
    "view_rotation",
    "render_mesh",
    "render_sequence",
    "write_png",
    "write_gif",
]
