"""TPU-native visualization: pure-JAX rasterizers, hard and soft.

The reference's visualization (C11, /root/reference/data_explore.py:1-18)
depends on an external OpenGL viewer (vctoolkit + transforms3d) to render
scan-pose animations to AVI. This subsystem replaces that with a
dependency-free, jittable software renderer — camera transforms
(pinhole, weak-perspective, and dataset K-matrix calibrations), a
z-buffer triangle rasterizer with Lambert shading and per-vertex colors
(fit-error heatmaps via ``error_colormap``), and pure-Python PNG/GIF/AVI
writers — plus the DIFFERENTIABLE renders the fitting subsystem
consumes: SoftRas-style soft silhouettes and a soft z-buffer depth
renderer, sharing the hard rasterizer's exact NDC→pixel mapping so
masks, depth maps, and shaded renders all line up pixel-for-pixel.
Whole animation clips render as one batched XLA program on TPU.
"""

from mano_hand_tpu.viz.camera import (
    Camera,
    IntrinsicsCamera,
    WeakPerspectiveCamera,
    from_intrinsics,
    look_at,
    view_rotation,
)
from mano_hand_tpu.viz.render import (
    error_colormap,
    render_mesh,
    render_sequence,
)
from mano_hand_tpu.viz.silhouette import soft_depth, soft_silhouette
from mano_hand_tpu.viz.png import write_png, write_gif
from mano_hand_tpu.viz.avi import write_avi, read_avi_info

__all__ = [
    "Camera",
    "IntrinsicsCamera",
    "WeakPerspectiveCamera",
    "from_intrinsics",
    "look_at",
    "view_rotation",
    "error_colormap",
    "render_mesh",
    "render_sequence",
    "soft_depth",
    "soft_silhouette",
    "write_png",
    "write_gif",
    "write_avi",
    "read_avi_info",
]
