"""Uncompressed AVI (RIFF) video writer — pure stdlib, no codecs.

The reference's animation demo renders to an AVI via its external OpenGL
viewer (/root/reference/data_explore.py:17-18, vctoolkit TriMeshViewer).
This closes that capability natively: [T, H, W, 3] uint8 frame stacks from
``viz.render_sequence`` become a spec-conformant AVI using the 'DIB '
(uncompressed 24-bit BGR) stream format every mainstream player accepts.
No external video dependency, mirroring the stdlib-only PNG/GIF writers.
"""

from __future__ import annotations

import struct
from pathlib import Path
from typing import Sequence, Union

import numpy as np

PathLike = Union[str, Path]

_AVIF_HASINDEX = 0x00000010
_AVIIF_KEYFRAME = 0x00000010


def _u8_frames(frames) -> np.ndarray:
    arr = np.asarray(frames)
    if arr.ndim != 4 or arr.shape[-1] != 3:
        raise ValueError(f"expected [T, H, W, 3] frames, got {arr.shape}")
    # Shared quantization with the PNG/GIF writers so all three formats
    # emit identical pixels for the same render.
    from mano_hand_tpu.viz.png import _to_u8

    return _to_u8(arr)


def _dib_frame(frame: np.ndarray, stride: int) -> bytes:
    """RGB top-down -> padded BGR bottom-up rows (the DIB layout)."""
    h, w, _ = frame.shape
    bgr = frame[::-1, :, ::-1]  # flip rows, swap channels
    row_bytes = w * 3
    if stride == row_bytes:
        return bgr.tobytes()
    padded = np.zeros((h, stride), np.uint8)
    padded[:, :row_bytes] = bgr.reshape(h, row_bytes)
    return padded.tobytes()


def write_avi(
    frames: Union[np.ndarray, Sequence[np.ndarray]],
    path: PathLike,
    fps: int = 20,
) -> Path:
    """Write [T, H, W, 3] frames (uint8 or float in [0,1]) as an AVI file.

    Single 'vids' stream, BI_RGB (uncompressed) 24-bit DIB frames, with the
    idx1 index chunk for seekable playback.
    """
    arr = _u8_frames(frames)
    t, h, w, _ = arr.shape
    if t == 0:
        raise ValueError("cannot write an AVI with zero frames")
    stride = (w * 3 + 3) & ~3  # DIB rows pad to 4-byte boundaries
    frame_size = stride * h

    def chunk(tag: bytes, payload: bytes) -> bytes:
        pad = b"\x00" if len(payload) % 2 else b""
        return tag + struct.pack("<I", len(payload)) + payload + pad

    def lst(kind: bytes, payload: bytes) -> bytes:
        return chunk(b"LIST", kind + payload)

    avih = struct.pack(
        "<10I4x12x",
        int(1_000_000 // max(fps, 1)),   # dwMicroSecPerFrame
        frame_size * fps,                # dwMaxBytesPerSec
        0,                               # dwPaddingGranularity
        _AVIF_HASINDEX,                  # dwFlags
        t,                               # dwTotalFrames
        0,                               # dwInitialFrames
        1,                               # dwStreams
        frame_size,                      # dwSuggestedBufferSize
        w,                               # dwWidth
        h,                               # dwHeight (+4x12x: 4 reserved I)
    )
    strh = struct.pack(
        "<4s4sIHHIIIIIIiI4H",
        b"vids", b"DIB ",
        0, 0, 0,                         # dwFlags, wPriority, wLanguage
        0,                               # dwInitialFrames
        1, max(fps, 1),                  # dwScale / dwRate = frame period
        0, t,                            # dwStart, dwLength (frames)
        frame_size,                      # dwSuggestedBufferSize
        -1, 0,                           # dwQuality (default), dwSampleSize
        0, 0, w, h,                      # rcFrame
    )
    # BITMAPINFOHEADER: biHeight > 0 declares bottom-up row order.
    strf = struct.pack(
        "<IiiHHIIiiII", 40, w, h, 1, 24, 0, frame_size, 0, 0, 0, 0
    )
    hdrl = lst(
        b"hdrl",
        chunk(b"avih", avih)
        + lst(b"strl", chunk(b"strh", strh) + chunk(b"strf", strf)),
    )

    # O(T) assembly: collect chunks in lists and join once (+= on bytes
    # would copy the whole growing buffer per frame).
    movi_parts = [b"movi"]
    index_parts = []
    offset = 4  # past the 'movi' fourcc
    for i in range(t):
        # idx1 offsets point at the chunk fourcc, relative to 'movi'.
        index_parts.append(struct.pack(
            "<4sIII", b"00db", _AVIIF_KEYFRAME, offset, frame_size
        ))
        frame_chunk = chunk(b"00db", _dib_frame(arr[i], stride))
        movi_parts.append(frame_chunk)
        offset += len(frame_chunk)
    movi = chunk(b"LIST", b"".join(movi_parts))

    riff_payload = b"AVI " + hdrl + movi + chunk(b"idx1", b"".join(index_parts))
    path = Path(path)
    with open(path, "wb") as f:
        f.write(chunk(b"RIFF", riff_payload))
    return path


def read_avi_info(path: PathLike) -> dict:
    """Parse an AVI's headers (and first frame) back — the test-side dual of
    ``write_avi``; also a quick integrity check for exported clips."""
    data = Path(path).read_bytes()
    if data[:4] != b"RIFF" or data[8:12] != b"AVI ":
        raise ValueError("not a RIFF/AVI file")
    (micro_per_frame, _, _, flags, total_frames, _, streams, _, width,
     height) = struct.unpack_from("<10I", data, data.index(b"avih") + 8)
    strf_off = data.index(b"strf") + 8
    (_, bw, bh, _, bits, compression, size_image) = struct.unpack_from(
        "<IiiHHII", data, strf_off
    )
    movi_off = data.index(b"movi")
    first_off = movi_off + 4
    tag, first_size = data[first_off:first_off + 4], struct.unpack_from(
        "<I", data, first_off + 4
    )[0]
    stride = (bw * 3 + 3) & ~3
    raw = np.frombuffer(
        data, np.uint8, count=first_size, offset=first_off + 8
    ).reshape(bh, stride)[:, : bw * 3].reshape(bh, bw, 3)
    first_frame = raw[::-1, :, ::-1]  # back to RGB top-down
    return {
        "width": width,
        "height": height,
        "n_frames": total_frames,
        "fps": round(1_000_000 / micro_per_frame) if micro_per_frame else 0,
        "streams": streams,
        "has_index": bool(flags & _AVIF_HASINDEX),
        "bits": bits,
        "compression": compression,
        "first_chunk_tag": tag.decode(),
        "first_frame": first_frame,
    }
